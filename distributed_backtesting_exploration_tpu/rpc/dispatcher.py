"""The dispatcher: job queue with leases, peer liveness, durable journal.

Capability superset of the reference's server (queue of file-backed jobs,
batch sizing by advertised capacity, peer registry with a liveness-pruning
thread, completion recording — reference ``src/server/main.rs``), with its
recorded defects designed out:

- peers are keyed by the worker-chosen ``worker_id``, not a socket address
  (the reference keyed by ``local_addr()`` — its own address — so all peers
  collapsed into one entry; reference ``src/server/main.rs:84,109``);
- batching is take-*n* (the reference's ``split_off(n)`` handed out
  ``len-n`` jobs — inverted semantics; reference ``src/server/main.rs:151-162``);
- every RPC refreshes liveness (the reference refreshed only on RequestJobs,
  so a busy worker that stopped polling was pruned while computing);
- an empty queue returns an empty reply, not an error with an OK code
  (reference ``src/server/main.rs:139-141``);
- handed-out jobs carry a lease; lease expiry or peer prune re-queues them
  (the retry the reference names as missing, reference ``README.md:82``);
- unreadable files are recorded as failed jobs, not silently dropped
  (reference ``src/server/main.rs:164-180`` filter_maps them away);
- the queue + completions journal to disk and replay on restart
  (reference ``README.md:80``: server crash loses everything).
"""

from __future__ import annotations

import argparse
import base64
import collections
import dataclasses
import functools
import glob as glob_mod
import json
import logging
import os
import threading
import time
import uuid
from concurrent import futures
from typing import Mapping

import numpy as np

from . import backtesting_pb2 as pb
from . import panel_store as panel_store_mod
from . import service, wire
from .journal import Journal
from .. import obs
from ..obs import decisions as obs_decisions
from ..obs import fleet as obs_fleet
from ..obs import flight as obs_flight
from ..runtime import _core as native_core
from ..sched import (DEFAULT_TENANT, WfqScheduler, held_explain,
                     placement as sched_placement, tenant_bucket)
from ..utils import data as data_mod

log = logging.getLogger("dbx.dispatcher")


def _lockdep_report() -> dict:
    """Flight-bundle source: the lockdep edge table + violations (empty
    shape when lockdep was never installed). Lazy import — analysis is
    a tooling package the serving path must not load eagerly."""
    from ..analysis import lockdep

    return lockdep.report()


# ---------------------------------------------------------------------------
# Job records and the leased queue
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JobRecord:
    """One dispatchable backtest job (a ticker's history x a param grid)."""

    id: str
    strategy: str
    grid: Mapping[str, np.ndarray]
    cost: float = 0.0
    periods_per_year: int = 252
    path: str | None = None       # file-backed source (CSV or DBX1)
    ohlcv: bytes | None = None    # inline source (already-encoded DBX1)
    ohlcv2: bytes | None = None   # second leg for two-legged strategies
    path2: str | None = None      # file-backed second leg (pairs --data2)
    # Walk-forward mode (proto JobSpec.wf_*): train/test bars per refit
    # window; 0 train = plain sweep. The DBXM result is then one stitched
    # out-of-sample metrics row, not a per-combo matrix.
    wf_train: int = 0
    wf_test: int = 0
    wf_metric: str = ""
    # On-device result reduction (proto JobSpec.top_k): when > 0 the worker
    # ships only the top-k param rows by rank_metric instead of the full
    # per-combo matrix — the DCN-diet mode for huge grids.
    top_k: int = 0
    rank_metric: str = ""
    # Fleet-portfolio mode (proto JobSpec.best_returns): the worker ships a
    # DBXP block — best combo by rank_metric + its net-return series — so
    # `aggregate --portfolio` can compose the true fleet book.
    best_returns: bool = False
    # Distributed tracing (proto JobSpec.trace_id): minted at enqueue time
    # (JobQueue.enqueue_many) and journaled, so a job keeps ONE trace id
    # across dispatcher restarts. enqueue_ts (wall clock) anchors the
    # queue-wait and end-to-end spans; deliberately NOT journaled — a
    # restart restarts the queue-wait clock rather than attributing the
    # outage to the queue.
    trace_id: str = ""
    enqueue_ts: float = 0.0
    # Content addresses (proto JobSpec.panel_digest/panel_digest2): the
    # blake2b-128 hex digest of each leg's DBX1 bytes, stamped at enqueue
    # (inline payloads) or first materialization (file-backed — a later
    # "digest" journal event merges into the enqueue record on replay).
    # Journaled so a restart keeps dispatching by the SAME address the
    # first run delivered; the blob store repopulates lazily from the
    # payload source.
    panel_digest: str = ""
    panel_digest2: str = ""
    # Streaming append jobs (proto AppendBars / JobSpec.append_*): the
    # base panel's content address, its bar count, and the appended
    # ΔT-bar DBX1 slice. The record carries NO full payload — the
    # extended panel materializes through the delta chain
    # (``JobQueue._splice_from_chain``), so enqueue records and journal
    # growth stay O(ΔT) per append.
    append_parent: str = ""
    append_base_len: int = 0
    delta: bytes | None = None
    # Placement-deferral bookkeeping (NOT journaled — locality evidence
    # dies with the process, so restarts restart locality cold): how many
    # times take() deferred this job for a better-scored worker (round
    # 20, sched.placement). At DBX_PLACEMENT_DEFER_CAP any worker serves
    # it. The field name survives from the round-6 one-shot append
    # affinity this budget generalized (record/decision-schema
    # stability).
    affinity_skips: int = 0
    # Multi-tenant serving (proto JobSpec.tenant_id): the weighted-fair-
    # queueing identity. proto3's default empty string — and a journal
    # record without the key — map to the `default` tenant, so legacy
    # clients and pre-tenancy journals keep exactly their old (FIFO)
    # behavior. Journaled so replay rebuilds per-tenant backlogs.
    tenant: str = DEFAULT_TENANT
    # Digest-seeded scenario synthesis (proto ScenarioSpec): when set,
    # this job's panel is a pure function of (scenario["base"] digest,
    # generator params) and materializes through the panel store like a
    # file-backed payload — the record itself stays payload-free.
    scenario: dict | None = None

    @property
    def combos(self) -> int:
        n = 1
        for v in self.grid.values():
            n *= max(int(np.asarray(v).size), 1)
        return n

    def journal_form(self) -> dict:
        rec = {"id": self.id, "strategy": self.strategy,
               "grid": {k: np.asarray(v).tolist() for k, v in self.grid.items()},
               "cost": self.cost, "ppy": self.periods_per_year}
        if self.path is not None:
            rec["path"] = self.path
        elif self.ohlcv is not None:
            # Inline payloads must be journaled too, or a restart would
            # restore a job with nothing to dispatch.
            rec["ohlcv_b64"] = base64.b64encode(self.ohlcv).decode("ascii")
        if self.path2 is not None:
            rec["path2"] = self.path2
        if self.ohlcv2 is not None:
            rec["ohlcv2_b64"] = base64.b64encode(self.ohlcv2).decode("ascii")
        if self.wf_train:
            rec["wf"] = [self.wf_train, self.wf_test, self.wf_metric]
        if self.top_k:
            rec["topk"] = [self.top_k, self.rank_metric]
        if self.best_returns:
            rec["ret"] = [True, self.rank_metric]
        if self.trace_id:
            rec["trace"] = self.trace_id
        if self.panel_digest:
            rec["pdig"] = self.panel_digest
        if self.panel_digest2:
            rec["pdig2"] = self.panel_digest2
        if self.append_parent:
            # The delta payload itself is journaled once as the chain's
            # `delta` event (keyed by pdig); the enqueue record carries
            # only the O(1) linkage.
            rec["apdig"] = self.append_parent
            rec["abase"] = self.append_base_len
        if self.tenant != DEFAULT_TENANT:
            # Default-tenant records stay slim (and byte-identical to
            # pre-tenancy journals); compaction drops only payload keys,
            # so the tenant survives onto slim terminal records too.
            rec["tenant"] = self.tenant
        if self.scenario is not None:
            rec["scn"] = self.scenario
        return rec

    @staticmethod
    def from_journal(rec: dict) -> "JobRecord":
        ohlcv = rec.get("ohlcv_b64")
        ohlcv2 = rec.get("ohlcv2_b64")
        wf = rec.get("wf") or [0, 0, ""]
        topk = rec.get("topk") or [0, ""]
        return JobRecord(
            id=rec["id"], strategy=rec["strategy"],
            grid={k: np.asarray(v, np.float32)
                  for k, v in rec.get("grid", {}).items()},
            cost=rec.get("cost", 0.0), periods_per_year=rec.get("ppy", 252),
            path=rec.get("path"), path2=rec.get("path2"),
            ohlcv=base64.b64decode(ohlcv) if ohlcv else None,
            ohlcv2=base64.b64decode(ohlcv2) if ohlcv2 else None,
            wf_train=int(wf[0]), wf_test=int(wf[1]), wf_metric=str(wf[2]),
            top_k=int(topk[0]),
            rank_metric=str(topk[1]) or str((rec.get("ret") or [0, ""])[1]),
            best_returns=bool((rec.get("ret") or [False])[0]),
            trace_id=str(rec.get("trace", "")),
            panel_digest=str(rec.get("pdig", "")),
            panel_digest2=str(rec.get("pdig2", "")),
            append_parent=str(rec.get("apdig", "")),
            append_base_len=int(rec.get("abase", 0)),
            tenant=str(rec.get("tenant", "")) or DEFAULT_TENANT,
            scenario=rec.get("scn"))


@dataclasses.dataclass
class Lease:
    worker_id: str
    deadline: float


class _PyQueueState:
    """Pure-Python fallback of the native job-queue state machine.

    Mirrors ``cpp/dbx_core.h``'s ``DbxJobQueue`` contract exactly (the
    same contract :class:`runtime._core.NativeJobQueue` binds); the parity
    tests in ``tests/test_rpc_unit.py`` run both substrates through
    identical scenarios. Not itself thread-safe — every call arrives under
    ``JobQueue._lock`` (single-lock discipline, matching how the native
    side is driven).
    """

    def __init__(self, clock=time.monotonic):
        # Injectable lease clock (defaults to the real monotonic clock):
        # the model checker drives lease expiry deterministically by
        # advancing a virtual clock instead of sleeping past deadlines.
        # The native substrate keeps its C-side clock — mc gets the same
        # determinism there with lease_s=0 (already-expired leases).
        self._clock = clock
        self._pending: collections.deque[str] = collections.deque()
        # Ids completed while still in the pending FIFO (late completions
        # from a previous lease): the FIFO supports no interior removal, so
        # take_begin skips tombstoned ids on pop. Invariant: every
        # tombstone refers to an id currently in the FIFO.
        self._tombstones: set[str] = set()
        self._combos: dict[str, float] = {}      # id -> combo credit
        self._leases: dict[str, Lease] = {}
        self._completed: dict[str, float] = {}   # id -> combos credited
        self._failed: set[str] = set()
        self._requeued = 0
        self._combos_done = 0.0

    def register(self, jid: str, combos: float) -> None:
        self._combos[jid] = float(combos)

    def push_pending(self, jid: str) -> None:
        self._pending.append(jid)

    def mark_completed(self, jid: str) -> None:
        # Journal-restore path: completed in a prior run, no throughput
        # credit for this run's combos_done.
        self._completed.setdefault(jid, 0.0)

    def mark_failed(self, jid: str) -> None:
        self._failed.add(jid)

    def take_begin(self) -> str | None:
        while self._pending:
            jid = self._pending.popleft()
            if jid in self._tombstones:     # completed while pending
                self._tombstones.discard(jid)
                continue
            return jid
        return None

    def take_commit(self, jid: str, worker_id: str, lease_s: float) -> bool:
        """False when the job completed in the take window (not leased)."""
        if self._discard_if_completed(jid):
            return False
        self._leases[jid] = Lease(worker_id, self._clock() + lease_s)
        return True

    def fail(self, jid: str) -> bool:
        """False when the job completed in the take window (not failed)."""
        if self._discard_if_completed(jid):
            return False
        self._failed.add(jid)
        return True

    def _discard_if_completed(self, jid: str) -> bool:
        """True if ``jid`` completed while take() held it outside the lock;
        clears the orphan tombstone complete() installed."""
        if jid in self._completed:
            self._tombstones.discard(jid)
            return True
        return False

    def complete(self, jid: str) -> str:
        if jid not in self._combos:
            return "unknown"
        had_lease = self._leases.pop(jid, None) is not None
        if jid in self._completed:
            return "dup"
        if (not had_lease and jid not in self._failed
                and jid not in self._tombstones):
            # Rare path: completion for a job sitting in the pending FIFO
            # (e.g. a completion RPC that straddled a lease expiry or
            # restart). The FIFO has no interior removal; tombstone the id
            # so take skips it instead of re-dispatching.
            self._tombstones.add(jid)
        combos = self._combos[jid]
        self._completed[jid] = combos
        self._combos_done += combos
        return "new"

    # Batch surface (one call per RPC-sized batch): trivial loops here —
    # the point of batching is the native substrate's ctypes crossing, but
    # both substrates expose the same methods so JobQueue stays agnostic.

    def enqueue_n(self, jids: list[str], combos: list[float]) -> None:
        for jid, c in zip(jids, combos):
            self.register(jid, c)
            self.push_pending(jid)

    def take_begin_n(self, n: int) -> list[str]:
        out = []
        while len(out) < n:
            jid = self.take_begin()
            if jid is None:
                break
            out.append(jid)
        return out

    def take_commit_n(self, jids: list[str], worker_id: str,
                      lease_s: float) -> list[bool]:
        return [self.take_commit(j, worker_id, lease_s) for j in jids]

    def complete_n(self, jids: list[str]) -> list[str]:
        return [self.complete(j) for j in jids]

    def requeue_expired(self) -> list[str]:
        now = self._clock()
        expired = [jid for jid, l in self._leases.items()
                   if l.deadline <= now]
        for jid in expired:
            del self._leases[jid]
            self._pending.appendleft(jid)
        self._requeued += len(expired)
        return expired

    def requeue_worker(self, worker_id: str) -> list[str]:
        held = [jid for jid, l in self._leases.items()
                if l.worker_id == worker_id]
        for jid in held:
            del self._leases[jid]
            self._pending.appendleft(jid)
        self._requeued += len(held)
        return held

    def stats(self) -> dict:
        return {"pending": len(self._pending) - len(self._tombstones),
                "leased": len(self._leases),
                "completed": len(self._completed),
                "requeued": self._requeued,
                "failed": len(self._failed),
                "combos_done": self._combos_done}

    def drained(self) -> bool:
        live_pending = len(self._pending) - len(self._tombstones)
        return live_pending == 0 and not self._leases


# Strategies AppendBars accepts: the streaming families that fit a
# one-panel wire (``streaming.recurrent._STREAM_FAMILIES`` minus pairs,
# whose second leg cannot ride an AppendRequest). A LITERAL set — the
# dispatcher process must not import the jax-backed streaming package
# just to validate a name; tests/test_streaming.py pins it against the
# real registry so the two cannot drift.
STREAMABLE_STRATEGIES = frozenset({
    "sma_crossover", "momentum", "bollinger", "bollinger_touch",
    "obv_trend", "stochastic", "vwap_reversion", "keltner", "rsi",
    "macd", "trix", "donchian", "donchian_hl"})


class JobQueue:
    """Thread-safe FIFO of JobRecords with leases and a durable journal.

    ``take`` materializes file-backed payloads at dispatch time (so enqueue
    is cheap and restarts don't re-read anything); a job whose file cannot
    be read is marked failed and journaled, never silently dropped.

    The id-state machine (pending FIFO + tombstones + lease table +
    completion idempotency) has two substrates passing identical parity
    tests: the pure-Python one (DEFAULT when driven from Python — at
    Python-call grain CPython's C-implemented dict/deque are already a
    native hash map with zero marshalling, and they measured at or above
    the ctypes-driven core even after the batch/int-handle redesign;
    DESIGN.md "queue state machine alone"), and the native C++ core
    (``cpp/dbx_core.h`` ``DbxJobQueue`` — the reference's whole dispatcher
    state is native, reference ``src/server/main.rs:20-190``), opt-in here
    via ``use_native=True`` / ``DBX_NATIVE_QUEUE=1`` and the ONLY
    substrate when driven from a native shell through the C ABI, where it
    does millions of transitions/s with no crossing at all
    (``cpp/dbx_core_bench.cc``). gRPC serving stays in Python (no grpc++
    in this environment). Full job records (grids, payloads, paths) stay
    Python-side keyed by the same ids.
    """

    def __init__(self, journal: Journal | None = None, *,
                 lease_s: float = 60.0, use_native: bool | None = None,
                 clock=None):
        self._lock = threading.Lock()
        self._records: dict[str, JobRecord] = {}
        state = None
        if use_native is None:
            use_native = (os.environ.get("DBX_NATIVE_QUEUE") == "1"
                          and native_core.available())
        if use_native:
            try:
                state = native_core.NativeJobQueue()
            except RuntimeError:
                state = None
        self.substrate = "native" if state is not None else "python"
        if state is not None:
            self._state = state
        else:
            # ``clock`` (model-checker seam): virtual lease clock for the
            # python substrate; ignored on native (C-side clock — mc uses
            # lease_s=0 there for the same determinism).
            self._state = (_PyQueueState(clock=clock) if clock is not None
                           else _PyQueueState())
        # Content-addressed blob store of materialized DBX1 panels: hot
        # panels and requeued jobs never touch disk (or re-transcode CSV)
        # twice, and FetchPayload serves cache-missing workers from it.
        # digest -> job id of SOME record carrying that digest (last
        # stamped wins): the lazy-repopulation index — an evicted blob
        # re-materializes from that record's source.
        self.panel_store = panel_store_mod.PanelStore()
        self._digest_jobs: dict[str, str] = {}
        # Streaming append chain: extended-panel digest -> (parent digest,
        # delta bytes, base bar count). Populated by append_bars() and by
        # journal replay (`delta` events); an evicted extended panel
        # re-materializes by walking parents back to a payload source and
        # re-splicing (deterministic, so digests stay stable).
        self._delta_chain: dict[str, tuple[str, bytes, int]] = {}
        # Python-side mirror of completed ids (the native core keeps only
        # counts): maintained on every "new" completion + restore, read by
        # observers (chaos tests, operators) via completed_ids().
        self._completed_ids: set[str] = set()
        self._journal = journal or Journal(None)
        self.known_paths: set[str] = set()
        # Journaled (leg-y path -> leg-x path) pairings for two-legged jobs:
        # the journal is the authority on which x file a y file was paired
        # with, so restart intake can keep new pairings disjoint from old
        # ones instead of trusting sort position (advisor finding: y-glob
        # churn with equal counts silently re-assigns x legs).
        self.known_pairings: dict[str, str] = {}
        self.journaled_jobs = 0
        self.lease_s = lease_s
        self._t0 = time.monotonic()
        # Jobs popped by take_begin but not yet committed/failed (payload
        # materialization runs outside the lock): drained must stay False
        # through that window or an observer could tear the dispatcher down
        # with a job mid-dispatch.
        self._in_take = 0
        # Placement-deferred jobs, held OUT of the FIFO so the next
        # take() serves them FIRST (front of line — a tail re-push would
        # park a latency-critical live update behind the whole batch
        # backlog). Journaled-pending either way, so a crash loses
        # nothing; held ids re-enter through the admit filter each
        # round, which is what lets a job wait up to the deferral cap.
        self._placement_held: list[str] = []
        # Pending-digest refcounts for the placement stage's chain-
        # settling rule: digest -> how many NOT-YET-DISPATCHED jobs
        # carry it as their panel digest. An append link whose parent
        # is still in here has no carry holder anywhere yet, so the
        # score table cannot route it — the admit gate defers it
        # (within the same affinity_skips budget) until the parent
        # settles. Counts move under self._lock: incremented at
        # intake, decremented at lease commit or intake-side failure.
        # NOT journaled (restarts restart locality cold, like the rest
        # of the placement state); rare refcount drift (requeue after
        # lease expiry re-leases without re-incrementing) is bounded
        # harm — the cap bounds any wait either way.
        self._pending_digests: dict[str, int] = {}
        # Weighted-fair-queueing index (sched.wfq): EVERY pending job is
        # parked in a per-tenant lane, held OUT of the state machine's
        # FIFO under the same discipline as _placement_held — enqueue
        # pushes through the state machine (register + FIFO) and
        # immediately drains the FIFO into the lanes under the same
        # lock, so the FIFO is empty between public calls and the WFQ
        # pick alone decides dispatch order. `drained`/stats fold the
        # parked count back in, so the accounting stays exact. Weights/
        # quotas read from DBX_TENANT_WEIGHTS / DBX_TENANT_QUOTA here
        # (one scheduler per queue, lazily — never at import).
        self._sched = WfqScheduler()
        # Scenario memo: (base digest, canonical params) -> generated
        # panel digest, so N jobs sharing one scenario spec regenerate
        # once, and re-materialization after eviction skips straight to
        # a store probe. Bounded LRU — specs are wire-controlled input,
        # and nothing may grow per spec ever seen; an evicted memo entry
        # merely costs one regeneration (same digest by construction).
        self._scenario_digests: collections.OrderedDict = \
            collections.OrderedDict()
        # Per-spec in-flight generation guard: concurrent takes of
        # scenario jobs sharing one spec must not each run the
        # generator (the gRPC pool could burn 16x duplicate work per
        # spec); losers wait for the winner's event and re-probe.
        self._scn_inflight: dict[tuple[str, str], threading.Event] = {}
        # Per-thread scenario resolution chain (scenario-of-scenario
        # bases are legal; a corrupted spec graph must degrade loudly,
        # not recurse forever).
        self._scn_tl = threading.local()

    # Native substrate cap (cpp/dbx_core.h DBX_JOBQ_MAX_ID); enforced at
    # intake on BOTH substrates so behavior cannot diverge at the edge.
    MAX_ID_BYTES = 511

    # Scenario spec -> digest memo bound (entries are ~150 B; eviction
    # costs one deterministic regeneration, never a different digest).
    MAX_SCENARIO_MEMO = 4096

    # -- intake ------------------------------------------------------------

    def enqueue(self, rec: JobRecord, *, journal: bool = True) -> None:
        self.enqueue_many([rec], journal=journal)

    def enqueue_many(self, recs: list[JobRecord], *,
                     journal: bool = True) -> None:
        """Intake a batch with ONE state-machine crossing (register + push
        for the whole batch); journal appends stay per record. Same
        semantics as per-record :meth:`enqueue`, batched for the same
        reason as take/complete: per-job ctypes crossings dominated the
        native substrate's cost."""
        for rec in recs:
            if len(rec.id.encode()) > self.MAX_ID_BYTES:
                raise ValueError(
                    f"job id exceeds {self.MAX_ID_BYTES} bytes (native "
                    f"substrate cap, enforced on both substrates): "
                    f"{rec.id[:64]!r}...")
            if "\0" in rec.id:
                # The native batch pack is NUL-separated; an embedded NUL
                # would desynchronize the id<->index mirror from the C
                # intern table (enforced on both substrates).
                raise ValueError(f"job id contains NUL: {rec.id[:64]!r}")
        # Trace minting happens HERE — before the journal append — so the
        # id a restart restores is the id the first run's spans carried.
        # enqueue_ts is re-stamped per process (see JobRecord).
        now = time.time()
        for rec in recs:
            if not rec.tenant:
                # Legacy intake (empty tenant anywhere) normalizes HERE,
                # before the journal append — records and lanes agree.
                rec.tenant = DEFAULT_TENANT
            if not rec.trace_id:
                rec.trace_id = obs.new_trace_id()
            if not rec.enqueue_ts:
                rec.enqueue_ts = now
            # Content-address inline payloads HERE — before the journal
            # append — so the digest a restart restores is the address the
            # first run delivered to workers. File-backed payloads stamp at
            # first materialization (take) via a "digest" journal event.
            if rec.ohlcv is not None and not rec.panel_digest:
                rec.panel_digest = self.panel_store.put(rec.ohlcv)
            if rec.ohlcv2 is not None and not rec.panel_digest2:
                rec.panel_digest2 = self.panel_store.put(rec.ohlcv2)
        if journal and self._journal.enabled:
            # enabled-guarded: journal_form b64-encodes the payload, which
            # the no-op journal would throw away. Journal BEFORE the state
            # push makes the batch takeable: a worker can lease a job the
            # instant it is published, and a crash before its enqueue
            # record landed would orphan that in-flight job (restore would
            # not know it existed — a batch-wide loss window). A
            # journaled-but-unpublished job is merely re-enqueued by
            # replay, so this order bounds the loss at zero.
            for rec in recs:
                self._journal.append("enqueue", **rec.journal_form())
        with self._lock:
            for rec in recs:
                self._records[rec.id] = rec
                # Lazy-repopulation index: restored records arrive with
                # journaled digests but an empty store; FetchPayload and
                # take() re-materialize through this map.
                if rec.panel_digest:
                    self._digest_jobs[rec.panel_digest] = rec.id
                    self._pending_digests[rec.panel_digest] = \
                        self._pending_digests.get(rec.panel_digest, 0) + 1
                if rec.panel_digest2:
                    self._digest_jobs[rec.panel_digest2] = rec.id
            self._state.enqueue_n([rec.id for rec in recs],
                                  [float(rec.combos) for rec in recs])
            # Drain the batch straight out of the state FIFO into the
            # per-tenant WFQ lanes (same lock, so the FIFO is never
            # observably non-empty): the state machine keeps owning
            # register/lease/completion, the lanes own dispatch ORDER.
            for jid in self._state.take_begin_n(len(recs)):
                r = self._records[jid]
                self._sched.push(jid, r.tenant, float(r.combos))

    def restore(self, journal_path: str) -> int:
        """Replay a journal; re-enqueue pending jobs. Returns count restored.

        Also records what the journal already covers — ``known_paths`` (every
        file path ever enqueued, completed or not) and ``journaled_jobs`` —
        so a restarted ``main()`` can skip re-enqueueing work the previous
        run already dispatched (advisor finding: rerunning the documented
        command line after a crash must not duplicate completed jobs).
        """
        state = Journal.replay(journal_path)
        with self._lock:
            # Chain BEFORE jobs: a restored append job's first take
            # materializes through it.
            for ndig, rec in state.deltas.items():
                self._delta_chain[ndig] = (
                    str(rec.get("pdig", "")),
                    base64.b64decode(rec.get("delta_b64", "")),
                    int(rec.get("base_len", 0)))
        n = 0
        for jid in state.pending:
            self.enqueue(JobRecord.from_journal(state.jobs[jid]),
                         journal=False)
            n += 1
        with self._lock:
            for jid in state.completed:
                self._state.mark_completed(jid)
                self._completed_ids.add(jid)
            for jid in state.failed:
                self._state.mark_failed(jid)
            # Register terminal jobs' (slim) records too: a late duplicate
            # completion arriving after a restart must be answered as an
            # idempotent "dup", not "unknown".
            for jid, rec in state.jobs.items():
                if jid not in self._records:
                    r = JobRecord.from_journal(rec)
                    self._records[jid] = r
                    self._state.register(jid, float(r.combos))
                    if r.panel_digest:
                        self._digest_jobs.setdefault(r.panel_digest, jid)
                    if r.panel_digest2:
                        self._digest_jobs.setdefault(r.panel_digest2, jid)
        with self._lock:
            # Rehydrate restored append jobs' delta bytes from the chain:
            # without them a post-restart dispatch would ship empty
            # ohlcv AND empty append_delta to a base-holding worker,
            # forcing a full-panel FetchPayload — undoing the O(ΔT) wire
            # saving the delta-only leg exists for.
            for rec in self._records.values():
                if rec.append_parent and rec.delta is None:
                    link = self._delta_chain.get(rec.panel_digest)
                    if link is not None:
                        rec.delta = link[1]
        self.known_paths |= {rec["path"] for rec in state.jobs.values()
                             if rec.get("path")}
        self.known_pairings.update(
            {rec["path"]: rec["path2"] for rec in state.jobs.values()
             if rec.get("path") and rec.get("path2")})
        self.journaled_jobs += len(state.jobs)
        return n

    # -- dispatch ----------------------------------------------------------

    def take(self, n: int, worker_id: str, admit=None,
             scenario_spec: dict | None = None,
             explain: dict | None = None
             ) -> list[tuple[JobRecord, bytes]]:
        """Pop up to ``n`` jobs, lease them to ``worker_id``, return payloads.

        Batched against the state machine: ONE ``take_begin_n`` crossing
        pops the batch, payloads materialize outside every lock, then ONE
        ``take_commit_n`` crossing leases the readable ones (per-id
        re-check inside: a job completed in the unlocked window is
        dropped, not leased and recomputed — the single-id race model,
        batch-wide). Per-job crossings made the native substrate slower
        than the dict fallback (DESIGN.md's 42k-vs-85k row); one crossing
        per RPC is the fix.

        ``admit`` is the placement hook (``rec -> bool``, consulted for
        EVERY popped record — round 20 generalized the append-only
        affinity special case away): a False verdict defers the job —
        held OUT of the FIFO (front of line: the NEXT take() call, from
        any worker, sees held jobs before the FIFO and runs them
        through its own admit again) — so a better-scored worker gets
        first claim without the job losing its place behind a batch
        backlog. The callback MUST bound its own deferrals
        (``JobRecord.affinity_skips`` is the budget the round-20
        placement gate spends, capped at ``DBX_PLACEMENT_DEFER_CAP``);
        a held job whose budget is spent is served to ANYONE, so
        placement can delay a job by a bounded number of poll rounds,
        never starve it. WFQ fairness is untouched: the pick (and its
        quota charge) happened before the hook runs, and a deferred job
        keeps its place at the front.

        ``scenario_spec`` (a dict, or None) opts the caller into the
        scenario-megakernel spec dispatch: an eligible scenario record
        whose BASE panel is servable skips materialization entirely —
        its returned payload is the BASE panel's bytes and the dict
        gains ``record id -> base digest`` so the caller can coalesce
        the records into spec-batch JobSpecs (the worker regenerates
        each panel in-trace). ``None`` (every legacy caller) keeps the
        materialized path verbatim, and so does any record that fails
        the eligibility gate — the fallback ladder is "don't coalesce",
        nothing else changes.

        ``explain`` (a dict, or None) opts into the round-19 decision
        plane: the WFQ pick-time explain record of every popped job
        lands under its id (a ``sched.explain.PickExplain``; jobs
        served from the affinity-held list get the minimal
        ``held_explain`` dict). Captured under the same lock as the
        pick itself, from the pick's own values — the record cannot
        drift from the decision, and ``None`` (every legacy caller)
        pays nothing. Serialization (``as_dict()``) is the consumer's
        job, off this path — the decision plane does it on its scoring
        thread.
        """
        out: list[tuple[JobRecord, bytes]] = []
        deferred: list[str] = []
        try:
            return self._take_inner(n, worker_id, admit, out, deferred,
                                    scenario_spec, explain)
        finally:
            if deferred:
                with self._lock:
                    # Held OUT of the FIFO, counted as in-take: `drained`
                    # must not flicker True with a live job in neither
                    # pending nor leased, and the next take() drains the
                    # held list before popping the FIFO.
                    self._placement_held.extend(deferred)

    def _digest_settled(self, digest: str) -> None:
        """Release one pending-digest refcount (caller holds ``_lock``):
        a job carrying this panel digest just left the pending pool —
        leased (the digest now HAS a holder the score table can route
        on) or failed at intake (it never will). ``get``-guarded: file-
        backed payloads stamp their digest at first materialization,
        AFTER intake counted nothing for them."""
        if not digest:
            return
        left = self._pending_digests.get(digest, 0) - 1
        if left > 0:
            self._pending_digests[digest] = left
        else:
            self._pending_digests.pop(digest, None)

    def _take_inner(self, n, worker_id, admit, out, deferred,
                    scenario_spec=None, explain=None):
        first = True
        while len(out) < n:
            with self._lock:
                jids = []
                if first:
                    # Previously placement-deferred jobs go first — they
                    # were at (or near) the FIFO head when deferred.
                    # They re-enter the admit loop below, so a job keeps
                    # deferring until its budget caps out.
                    first = False
                    k = min(len(self._placement_held), n - len(out))
                    if k:
                        jids = self._placement_held[:k]
                        self._placement_held = self._placement_held[k:]
                        # Already counted in _in_take while held; the
                        # per-iteration accounting below re-counts every
                        # id in `jids`, so release the held count here.
                        self._in_take -= k
                        if explain is not None:
                            for j in jids:
                                explain[j] = held_explain(j)
                # The WFQ pick replaces the FIFO pop: lowest virtual
                # start tag across tenant lanes, quota-demoted tenants
                # behind everyone else (sched.wfq).
                exp_list = [] if explain is not None else None
                jids += self._sched.pick(n - len(out) - len(jids),
                                         explain=exp_list)
                if exp_list:
                    for e in exp_list:
                        explain[e.jid] = e
                if not jids:
                    break
                # A popped id with no record is a state/record desync
                # (cannot happen through the public intake path, which
                # registers the record first) — fail it loudly instead of
                # crashing with the whole batch in limbo.
                desynced = [j for j in jids if j not in self._records]
                for j in desynced:
                    self._state.fail(j)
                    self._sched.release(j)
                jids = [j for j in jids if j not in desynced]
                recs = [self._records[j] for j in jids]
                n_deferred0 = len(deferred)
                if admit is not None:
                    kept_j, kept_r = [], []
                    for j, r in zip(jids, recs):
                        # ONE admit call per rec: the callback counts its
                        # own deferrals on the record.
                        if not admit(r):
                            deferred.append(j)
                        else:
                            kept_j.append(j)
                            kept_r.append(r)
                    jids, recs = kept_j, kept_r
                # Deferred ids count as in-take for as long as they sit
                # in _placement_held (neither pending nor leased); the
                # count releases when a later take() re-serves them.
                self._in_take += len(jids) + len(deferred) - n_deferred0
            good: list[tuple[str, JobRecord, bytes]] = []
            # id, path, err, stored record (the record rides along so the
            # fail path can close the job's trace and hand the flight
            # recorder a stitchable subject).
            failed: list[tuple[str, str, Exception, JobRecord]] = []
            resolved: set[str] = set()   # leased, failed, or completed
            stamped: list[tuple[str, JobRecord]] = []  # first-materialized
            try:
                # Inside the try: a journal error here must still reach
                # the push-back handler / _in_take decrement below, or
                # the rest of the popped batch is stranded.
                for j in desynced:
                    log.error("job %s: popped with no record (state "
                              "desync) -> failed", j)
                    self._journal.append("fail", id=j,
                                         reason="no job record")
                    obs_flight.trigger("job_fail", subject=j,
                                       reason="no job record")
                for jid, stored in zip(jids, recs):
                    rec = stored
                    payload = stored.ohlcv
                    try:
                        if (payload is None and scenario_spec is not None
                                and self._scenario_spec_eligible(stored)):
                            # Scenario megakernel spec dispatch: serve
                            # the BASE panel's bytes instead of
                            # generating the scenario panel — the worker
                            # regenerates it in-trace inside the fused
                            # sweep. An unservable base simply drops
                            # through to the materialized rung below
                            # (whose own triage decides loud-fail vs
                            # serve), so eligibility can never turn a
                            # dispatchable job into a failed one.
                            base_d = str(stored.scenario.get("base", ""))
                            blob = self.payload_for_digest(base_d)
                            if blob is not None:
                                scenario_spec[jid] = base_d
                                good.append((jid, stored, blob))
                                continue
                        if payload is None:
                            # Store-first materialization: a hot panel or
                            # a requeued/retried job never re-reads (or
                            # re-transcodes) the file. The digest stamps
                            # the STORED record on first materialization
                            # and is journaled below, so restarts keep the
                            # address stable.
                            payload, d = self._materialize(
                                stored.panel_digest, stored.path,
                                scenario=stored.scenario)
                            if d != stored.panel_digest:
                                stored.panel_digest = d
                                stamped.append((jid, stored))
                        if stored.ohlcv2 is None and stored.path2 is not None:
                            # File-backed second leg (pairs --data2):
                            # materialize at dispatch time like leg 1,
                            # onto a COPY handed to the caller — the
                            # stored record stays slim, and RequestJobs
                            # reads rec.ohlcv2 either way.
                            blob2, d2 = self._materialize(
                                stored.panel_digest2, stored.path2)
                            if d2 != stored.panel_digest2:
                                stored.panel_digest2 = d2
                                stamped.append((jid, stored))
                            rec = dataclasses.replace(stored, ohlcv2=blob2)
                    except (OSError, ValueError) as e:
                        # Leg 1 read fine -> the unreadable file was leg 2.
                        failed.append((
                            jid,
                            stored.path2 if payload is not None
                            else stored.path,
                            e, stored))
                        continue
                    good.append((jid, rec, payload))
                with self._lock:
                    for jid, r in stamped:
                        if r.panel_digest:
                            self._digest_jobs[r.panel_digest] = jid
                        if r.panel_digest2:
                            self._digest_jobs[r.panel_digest2] = jid
                    committed = self._state.take_commit_n(
                        [jid for jid, _, _ in good], worker_id,
                        self.lease_s)
                    # The quota charge landed at PICK (so concurrent
                    # takes can't both read a stale zero); here it is
                    # confirmed for leased ids and released for ids
                    # that fell out (completed mid-take — complete()
                    # already released, release is idempotent).
                    for ok, (jid, r, _) in zip(committed, good):
                        if ok:
                            self._sched.on_lease(jid, r.tenant,
                                                 float(r.combos))
                            # The digest has a holder now: any chain
                            # child waiting on it can route on the next
                            # table refresh instead of burning polls.
                            self._digest_settled(r.panel_digest)
                        else:
                            self._sched.release(jid)
                    # Every triaged id is resolved — including a failed-
                    # triage id whose fail() returns False below because
                    # a completion landed mid-take: that job is DONE, and
                    # the push-back handler must not return it to pending.
                    resolved = {jid for jid, _, _ in good}
                    resolved.update(jid for jid, _, _, _ in failed)
                    # Unreadable payloads fail under the same lock (the
                    # per-id re-check drops jobs completed mid-take);
                    # either way the pick-time quota charge releases.
                    for jid, _, _, _ in failed:
                        self._sched.release(jid)
                    failed = [(jid, path, e, r)
                              for jid, path, e, r in failed
                              if self._state.fail(jid)]
                    for _, _, _, r in failed:
                        # A failed job's digest will never be held —
                        # release the refcount so chain children stop
                        # waiting on it before their cap runs out.
                        self._digest_settled(r.panel_digest)
                for jid, path, e, r in failed:
                    log.error("job %s: unreadable %s (%s) -> failed",
                              jid, path, e)
                    self._journal.append("fail", id=jid, reason=str(e))
                    # Close the job's trace before the black-box fires:
                    # the flight bundle's stitched timeline must cover
                    # the job end-to-end even though it never dispatched
                    # (enqueue -> failure is its whole life).
                    if r.trace_id and r.enqueue_ts:
                        now_w = time.time()
                        wait = max(now_w - r.enqueue_ts, 0.0)
                        obs.emit_span("job.queue_wait", r.enqueue_ts,
                                      wait, trace_id=r.trace_id, job=jid)
                        obs.emit_span("job", r.enqueue_ts, wait,
                                      trace_id=r.trace_id, job=jid,
                                      ok=False)
                    obs_flight.trigger("job_fail", subject=jid,
                                       job=jid, path=str(path),
                                       reason=str(e))
                # Durable digest stamps (first materialization only — one
                # event per job, merged into its enqueue record on replay
                # and at compaction): a restarted dispatcher keeps
                # addressing the panel a prior run already delivered.
                for jid, r in dict(stamped).items():
                    self._journal.append(
                        "digest", id=jid, pdig=r.panel_digest,
                        **({"pdig2": r.panel_digest2}
                           if r.panel_digest2 else {}))
                out.extend((rec, payload)
                           for ok, (_, rec, payload) in zip(committed, good)
                           if ok)
            except BaseException:
                # Anything unexpected between the pop and the commit would
                # otherwise strand the WHOLE popped batch — neither
                # pending, leased, completed, nor failed, and invisible to
                # lease expiry — while drained() flips True. Push the
                # unresolved ids back to pending before propagating.
                with self._lock:
                    unresolved = [j for j in jids if j not in resolved]
                    for jid in unresolved:
                        self._sched.release(jid)
                    self._sched.requeue_front([
                        (jid, self._records[jid].tenant,
                         float(self._records[jid].combos))
                        for jid in unresolved])
                raise
            finally:
                with self._lock:
                    self._in_take -= len(jids)
        return out

    def _scenario_spec_eligible(self, rec: "JobRecord") -> bool:
        """Can this record ride the scenario-megakernel spec dispatch?
        Plain single-asset scenario sweeps of a fused-supported family
        only — any reduction/windowing mode, a second leg, or a
        digestless base keeps the record on the materialized rung (the
        degradation ladder's "don't coalesce" answer, never an error).
        The kernel-family probe imports ops.fused lazily: the dispatcher
        stays jax-free until a spec-capable worker actually polls with
        scenario records queued — the same moment the alternative was a
        full generator run."""
        if (rec.scenario is None or rec.append_parent or rec.wf_train
                or rec.top_k or rec.best_returns
                or rec.ohlcv2 is not None or rec.path2 is not None):
            return False
        if not str(rec.scenario.get("base", "")):
            return False
        try:
            from ..ops import fused
        except Exception:          # noqa: BLE001 — kernel stack absent
            return False
        return bool(fused.scenario_supported(rec.strategy))

    def _materialize(self, digest: str, path: str | None,
                     scenario: dict | None = None) -> tuple[bytes, str]:
        """One leg's payload bytes + content digest, blob store first.

        Only reads (and CSV/Parquet-transcodes) ``path`` — or regenerates
        a ``scenario`` panel — when the store cannot serve ``digest``:
        the second and every later take of a hot panel, and every
        requeue/retry, never touch disk (or the generator) again. The
        returned digest is always the digest OF THE RETURNED BYTES (a file
        whose content changed between materializations re-addresses; the
        caller re-stamps and journals)."""
        if digest:
            blob = self.panel_store.get(digest)
            if blob is not None:
                return blob, digest
        if path is None:
            if scenario is not None:
                # Digest-seeded synthesis: the panel is a pure function
                # of (base digest, params) — regeneration under the same
                # spec re-derives the same bytes, hence the same address.
                return self._scenario_payload(scenario, digest)
            if digest:
                # Streaming append jobs carry no payload source of their
                # own: the extended panel rebuilds from the delta chain.
                blob = self._splice_from_chain(digest)
                if blob is not None:
                    return blob, digest
            raise ValueError("job has neither payload nor path")
        blob = _read_payload(path)
        return blob, self.panel_store.put(blob)

    def _scenario_payload(self, scn: dict,
                          digest_hint: str = "") -> tuple[bytes, str]:
        """Materialize a scenario job's panel: memo/store first, else
        resolve the base panel (any payload source, incl. the append
        chain and nested scenario specs) and run the generator. Raises
        ``ValueError`` when the base is unservable or the spec invalid —
        the take() triage then fails the ONE job loudly, exactly like an
        unreadable file."""
        from .. import scenarios as scenarios_mod

        params = scenarios_mod.ScenarioParams.from_dict(scn)
        base_digest = str(scn.get("base", ""))
        key = (base_digest, params.canonical())
        # Cycle check BEFORE the single-flight gate: a corrupt
        # self-referential spec chain re-enters this method on the same
        # thread — it must raise loudly here, not wait on its own event.
        if base_digest in getattr(self._scn_tl, "seen", ()):
            raise ValueError(
                f"scenario base chain cycles at {base_digest[:16]}")
        while True:
            with self._lock:
                digest = self._scenario_digests.get(key, "") or digest_hint
                if key in self._scenario_digests:
                    self._scenario_digests.move_to_end(key)
            if digest:
                blob = self.panel_store.get(digest)
                if blob is not None:
                    return blob, digest
            # Single-flight per spec: the first thread generates, racers
            # wait on its event and re-probe (a failed/evicted result
            # makes the waiter take over — never a hang; spec references
            # form a DAG, so cross-thread waits cannot cycle).
            with self._lock:
                ev = self._scn_inflight.get(key)
                if ev is None:
                    ev = self._scn_inflight[key] = threading.Event()
                    break
            ev.wait(timeout=120.0)
        try:
            return self._scenario_generate(scn, key, params, base_digest)
        finally:
            with self._lock:
                self._scn_inflight.pop(key, None)
            ev.set()

    def _scenario_generate(self, scn: dict, key, params,
                           base_digest: str) -> tuple[bytes, str]:
        """The generation half of :meth:`_scenario_payload` (runs as the
        per-spec single-flight winner)."""
        from .. import scenarios as scenarios_mod

        seen = getattr(self._scn_tl, "seen", None)
        if seen is None:
            seen = self._scn_tl.seen = set()
        if base_digest in seen:
            raise ValueError(
                f"scenario base chain cycles at {base_digest[:16]}")
        seen.add(base_digest)
        try:
            base = self._payload_from_source(base_digest)
            if base is None:
                base = self._splice_from_chain(base_digest)
            if base is None:
                raise ValueError(
                    f"scenario base {base_digest[:16]} not servable "
                    "(store evicted and no job carries its source)")
        finally:
            seen.discard(base_digest)
        blob = scenarios_mod.scenario_panel_bytes(base, params)
        d = self.panel_store.put(blob)
        with self._lock:
            self._scenario_digests[key] = d
            self._scenario_digests.move_to_end(key)
            while len(self._scenario_digests) > self.MAX_SCENARIO_MEMO:
                self._scenario_digests.popitem(last=False)
        return blob, d

    def _splice_from_chain(self, digest: str) -> bytes | None:
        """Rebuild an extended panel from its journaled append chain:
        walk parents down to the nearest servable payload source, then
        splice every delta back up, storing each level — so the NEXT
        lookup anywhere on the chain is a store hit. Iterative with a
        visited-set guard (content digests cannot cycle by construction,
        but a corrupted journal must degrade, not hang): an arbitrarily
        long live stream stays servable after a restart. None when the
        chain is broken (no ancestor has a payload source) — the caller
        degrades exactly like an evicted ordinary digest."""
        chain: list[tuple[str, bytes]] = []
        seen: set[str] = set()
        d = digest
        base = None
        while True:
            if d in seen:
                log.error("append chain for %s cycles at %s; unservable",
                          digest[:16], d[:16])
                return None
            seen.add(d)
            with self._lock:
                link = self._delta_chain.get(d)
            if link is None:
                return None          # broken before any payload source
            parent, delta, _base_len = link
            chain.append((d, delta))
            base = self._payload_from_source(parent)
            if base is not None:
                break
            d = parent
        for d, delta in reversed(chain):
            try:
                base = data_mod.splice_wire_bytes(base, delta)
            except ValueError as e:
                log.error("append chain for %s does not splice (%s); "
                          "unservable", digest[:16], e)
                return None
            self.panel_store.put(base, d)
        return base

    def payload_for_digest(self, digest: str) -> bytes | None:
        """Serve a FetchPayload request: blob store first, then lazy
        re-materialization from the indexed record's source (inline bytes,
        file, or the streaming delta chain — the restart path: journaled
        digests arrive before any blob does). None when the digest is not
        servable at all (store evicted AND source gone or changed) — the
        dispatcher then forgets it was delivered so the next dispatch
        ships full bytes."""
        blob = self._payload_from_source(digest)
        if blob is not None:
            return blob
        # Append jobs have no payload source of their own — the extended
        # panel rebuilds from the journaled delta chain.
        return self._splice_from_chain(digest)

    def _payload_from_source(self, digest: str) -> bytes | None:
        """Store + record-source half of :meth:`payload_for_digest` (NO
        chain fallback — the chain walk calls this per ancestor)."""
        if not digest:
            return None
        blob = self.panel_store.get(digest)
        if blob is not None:
            return blob
        with self._lock:
            jid = self._digest_jobs.get(digest)
            rec = self._records.get(jid) if jid else None
        if rec is None:
            return None
        for inline, path, d in ((rec.ohlcv, rec.path, rec.panel_digest),
                                (rec.ohlcv2, rec.path2,
                                 rec.panel_digest2)):
            if d != digest:
                continue
            if inline is not None:
                self.panel_store.put(inline, digest)
                return inline
            if path is not None:
                try:
                    blob = _read_payload(path)
                except (OSError, ValueError):
                    return None
                if panel_store_mod.panel_digest(blob) != digest:
                    return None   # source changed under the address
                self.panel_store.put(blob, digest)
                return blob
        if rec.scenario is not None and rec.panel_digest == digest:
            # Evicted scenario panel: re-derive it from the spec (pure
            # function of base digest + params — the regenerated bytes
            # carry the SAME address, verified before serving).
            try:
                blob, d = self._scenario_payload(rec.scenario, digest)
            except ValueError:
                return None
            return blob if d == digest else None
        return None

    def extend_chain(self, parent_digest: str, base_len: int,
                     delta: bytes) -> tuple[str, str, int]:
        """Splice ``delta`` onto the stored base panel and journal the
        chain link — the tick half of AppendBars, shared by the job
        template AND the subscription tier's per-stream advances (one
        splice per tick, however many streams fan out of it).

        Returns ``(outcome, new_digest, new_len)``; a reject outcome
        (``base_missing`` / ``bad_delta`` / ``base_len_mismatch``)
        carries an empty digest (``base_len_mismatch`` reports the REAL
        base length in ``new_len`` for caller re-sync). Journal order:
        the ``delta`` event lands BEFORE any job's enqueue record, so a
        restored append job always finds its chain; a crash in between
        merely leaves a harmless orphan link."""
        base = self.payload_for_digest(parent_digest)
        if base is None:
            return "base_missing", "", 0
        base_series = data_mod.from_wire_bytes(base)
        if base_len and base_len != base_series.n_bars:
            # Stale feed guard, checked BEFORE any splice work: the
            # caller believes a different history length than the stored
            # base — appending would silently misalign every later bar.
            # Reject near-free; the caller re-syncs off the reply's
            # digest/new_len.
            return "base_len_mismatch", "", base_series.n_bars
        try:
            d_series = data_mod.from_wire_bytes(delta)
            if d_series.n_bars < 1:
                raise ValueError("empty delta slice")
        except ValueError:
            return "bad_delta", "", 0
        # One decode each + one encode (splice_wire_bytes would re-decode
        # both blobs — the live-serving hot path skips that).
        blob = data_mod.to_wire_bytes(data_mod.OHLCV(*(
            np.concatenate([np.asarray(b), np.asarray(d)])
            for b, d in zip(base_series, d_series))))
        ndig = self.panel_store.put(blob)
        new_len = base_series.n_bars + d_series.n_bars
        if self._journal.enabled:
            self._journal.append(
                "delta", ndig=ndig, pdig=parent_digest,
                base_len=base_series.n_bars,
                delta_b64=base64.b64encode(delta).decode("ascii"))
        with self._lock:
            self._delta_chain[ndig] = (parent_digest, delta,
                                       base_series.n_bars)
        return "extended", ndig, new_len

    def make_append_record(self, ndig: str, *, strategy: str, grid,
                           cost: float = 0.0, periods_per_year: int = 252,
                           tenant: str = DEFAULT_TENANT
                           ) -> JobRecord | None:
        """A repricing JobRecord for the extended panel ``ndig`` (NOT
        enqueued — the caller may need to index the id first, e.g. the
        subscription hub's register-before-enqueue discipline). The
        append linkage (parent, base length, delta bytes) comes from the
        chain ``extend_chain`` just recorded; None when ``ndig`` has no
        chain link (caller bug or a raced restart)."""
        with self._lock:
            link = self._delta_chain.get(ndig)
        if link is None:
            return None
        parent, delta, base_n = link
        return JobRecord(
            id=str(uuid.uuid4()), strategy=strategy, grid=grid,
            cost=float(cost), periods_per_year=int(periods_per_year),
            panel_digest=ndig, append_parent=parent,
            append_base_len=base_n, delta=delta,
            tenant=tenant or DEFAULT_TENANT)

    def append_bars(self, parent_digest: str, base_len: int, delta: bytes,
                    *, strategy: str, grid, cost: float = 0.0,
                    periods_per_year: int = 252,
                    tenant: str = DEFAULT_TENANT
                    ) -> tuple[JobRecord | None, str, str, int]:
        """Streaming live-bar ingest (the AppendBars RPC's queue half):
        :meth:`extend_chain` + one enqueued repricing job for the
        extended panel. An EMPTY ``strategy`` is a tick-only append —
        the chain extends (and the subscription tier's advances ride
        it, dispatcher-side) but no template job enqueues.

        Returns ``(record, outcome, new_digest, new_len)`` — record None
        with a reject outcome (``unsupported_strategy`` /
        ``base_missing`` / ``bad_delta`` / ``base_len_mismatch``) when
        nothing was enqueued, and None with ``extended`` for tick-only
        appends.
        """
        if strategy and strategy not in STREAMABLE_STRATEGIES:
            # Reject synchronously — enqueueing would burn a dispatch
            # round trip only for the worker to complete it loudly empty
            # (pairs cannot stream over a one-panel wire; unknown
            # families have no carry).
            return None, "unsupported_strategy", "", 0
        outcome, ndig, new_len = self.extend_chain(parent_digest,
                                                   base_len, delta)
        if outcome != "extended":
            return None, outcome, ndig, new_len
        rec = None
        if strategy:
            rec = self.make_append_record(
                ndig, strategy=strategy, grid=grid, cost=cost,
                periods_per_year=periods_per_year, tenant=tenant)
            self.enqueue(rec)
        return rec, "extended", ndig, new_len

    def complete(self, jid: str, worker_id: str) -> str:
        """Record a completion (idempotent). Returns ``"new"`` for a first
        completion, ``"dup"`` for a known-and-already-completed id, and
        ``"unknown"`` for ids the queue has never seen. (The new/dup split
        lets batched-completion replies report only newly-recorded jobs, so
        a worker retrying a deadline-expired-but-processed RPC does not
        over-count its own jobs_completed.)

        Handles late/duplicate completions from retrying workers: the lease is
        always cleared (a re-leased job completed twice must not pin a ghost
        lease), and a job completed while still pending (e.g. a completion
        RPC that straddled a dispatcher restart) is pulled out of the queue so
        it is not dispatched again.
        """
        with self._lock:
            outcome = self._state.complete(jid)
            if outcome != "new":
                return outcome
            self._completed_ids.add(jid)
            self._finish_complete(jid)
        self._journal.append("complete", id=jid, worker=worker_id)
        return "new"

    def _finish_complete(self, jid: str) -> None:
        """Scheduler bookkeeping for a first ("new") completion; caller
        holds ``self._lock``. A completion for a job still PARKED in a
        WFQ lane (a late completion that straddled a requeue or restart)
        leaves the state machine with an orphan tombstone — its FIFO is
        empty under the lane discipline. Discard the lane entry and
        drive the state's documented completed-in-the-take-window path
        (``take_commit`` on a completed id returns False and clears the
        tombstone) so pending counts and ``drained`` stay exact instead
        of waiting for the next worker poll to sweep it. The quota
        charge releases either way (idempotent). No suppression needed:
        interprocedural lock-discipline proves every caller holds the
        lock."""
        if self._sched.discard(jid):
            self._state.take_commit(jid, "wfq", self.lease_s)
        self._sched.release(jid)

    def complete_batch(self, jids: list[str], worker_id: str, *,
                       journal: bool = True) -> list[str]:
        """Batched :meth:`complete`: one state-machine crossing for a
        whole CompleteJobs RPC (per-id outcomes identical — the batch
        exists because per-job ctypes crossings made the native substrate
        slower than the dict fallback).

        ``journal=False`` defers the durable 'complete' records so the
        caller can persist the result blocks FIRST and then call
        :meth:`journal_completions` — a journaled-complete whose .dbxm
        block never landed is unrecoverable (the job is never
        re-dispatched), and with batched RPCs that window would span a
        whole batch, not one job. A crash in the
        state-complete-but-unjournaled window merely re-runs the batch
        after restart (idempotent overwrite).
        """
        if not jids:
            return []
        with self._lock:
            outcomes = self._state.complete_n(jids)
            for jid, outcome in zip(jids, outcomes):
                if outcome == "new":
                    self._completed_ids.add(jid)
                    self._finish_complete(jid)
        if journal:
            for jid, outcome in zip(jids, outcomes):
                if outcome == "new":
                    self._journal.append("complete", id=jid,
                                         worker=worker_id)
        return outcomes

    def journal_completions(self, jids: list[str], worker_id: str) -> None:
        """Durable 'complete' records for ids whose result blocks the
        caller has already persisted (the deferred half of
        ``complete_batch(journal=False)``)."""
        for jid in jids:
            self._journal.append("complete", id=jid, worker=worker_id)

    def completed_ids(self) -> set[str]:
        """Snapshot of completed job ids (restored + this run's)."""
        with self._lock:
            return set(self._completed_ids)

    def job_trace(self, jid: str) -> tuple[str, float]:
        """``(trace_id, enqueue_ts)`` of a known job, ``("", 0.0)`` for
        unknown ids — the completion handlers' lookup for closing the
        job's end-to-end span (the queue's record is authoritative; the
        wire echo on CompleteItem is advisory)."""
        with self._lock:
            rec = self._records.get(jid)
            return (rec.trace_id, rec.enqueue_ts) if rec else ("", 0.0)

    # -- recovery ----------------------------------------------------------

    def requeue_expired(self) -> list[str]:
        """Re-queue jobs whose lease deadline passed (front of the queue)."""
        with self._lock:
            jids = self._state.requeue_expired()
            self._repark_requeued(jids)
            self._restart_queue_wait(jids)
            return jids

    def requeue_worker(self, worker_id: str) -> list[str]:
        """Re-queue every job leased to a (pruned) worker."""
        with self._lock:
            jids = self._state.requeue_worker(worker_id)
            self._repark_requeued(jids)
            self._restart_queue_wait(jids)
            return jids

    def _repark_requeued(self, jids: list[str]) -> None:
        """Move just-requeued ids from the state FIFO (where requeue_*
        push-fronts them) into their tenants' WFQ lanes, preserving the
        FIFO's service order at the lane FRONTS — a retried job keeps
        its requeue-at-front latency class instead of re-waiting behind
        the tenant's whole backlog. Also releases the quota charge
        (the lease is gone). Caller holds ``self._lock``; the FIFO is
        empty outside this window, so the drain pops exactly ``jids``."""
        if not jids:
            return
        for jid in jids:
            self._sched.release(jid)
        self._sched.requeue_front([
            (jid, rec.tenant if rec else DEFAULT_TENANT,
             float(rec.combos) if rec else 1.0)
            for jid in self._state.take_begin_n(len(jids))
            for rec in (self._records.get(jid),)])

    def _restart_queue_wait(self, jids: list[str]) -> None:
        # A requeued job re-enters the pending pool NOW: restart its
        # queue-wait clock (same semantics as a journal restore) so the
        # re-dispatch's queue_wait span covers the second wait — not the
        # failed first attempt's whole lifetime, which would override
        # the attempt's own spans in timeline attribution.
        now = time.time()
        for jid in jids:
            rec = self._records.get(jid)
            if rec is not None:
                rec.enqueue_ts = now

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            s = self._state.stats()
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            return {
                # Pending = WFQ-parked jobs (the state FIFO is empty
                # between calls under the lane discipline; the sum keeps
                # the count exact through transient windows).
                "jobs_pending": s["pending"] + self._sched.pending(),
                "jobs_leased": s["leased"],
                "jobs_completed": s["completed"],
                "jobs_requeued": s["requeued"],
                "jobs_failed": s["failed"],
                "backtests_per_sec": s["combos_done"] / elapsed,
            }

    def tenant_stats(self) -> dict[str, dict]:
        """Per-tenant scheduling snapshot (parked backlog, in-flight
        combo charge, virtual finish, demotions) — the source behind the
        ``dbx_tenant_queue_jobs{tenant=...}`` gauge family."""
        with self._lock:
            return self._sched.stats()

    @property
    def drained(self) -> bool:
        with self._lock:
            # _in_take covers jobs popped but not yet leased/failed (payload
            # read in flight); WFQ-parked jobs are live pending work held
            # out of the state FIFO: drained must not flicker True while
            # either is non-zero.
            return (self._in_take == 0 and self._sched.pending() == 0
                    and self._state.drained())


def _read_payload(path: str) -> bytes:
    """Read a job's OHLCV payload; CSV and Parquet files are transcoded to
    DBX1 binary (format sniffed by magic: ``PAR1`` = Parquet, ``DBX1`` =
    already wire-ready, anything else = CSV)."""
    t0 = time.perf_counter()
    with open(path, "rb") as fh:
        raw = fh.read()
    if raw[:4] == b"PAR1":
        raw = data_mod.to_wire_bytes(data_mod.from_parquet_bytes(raw))
    elif raw[:4] != b"DBX1":
        series = data_mod.from_csv_bytes(raw)
        raw = data_mod.to_wire_bytes(series)
    log.info("read %s (%d bytes) in %.1fms",
             path, len(raw), 1e3 * (time.perf_counter() - t0))
    return raw


# ---------------------------------------------------------------------------
# Peer registry + liveness pruning
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Peer:
    status: int = pb.WORKER_STATUS_IDLE
    chips: int = 0
    last_seen: float = 0.0


class PeerRegistry:
    """Live workers keyed by stable worker_id; any RPC refreshes liveness.

    Liveness timing (last-seen stamping + windowed pruning — the hot path
    touched by every RPC and the maintenance thread) runs on the native C++
    registry when available (SURVEY.md §2.2 native ledger; the reference's
    pruning loop is native, reference ``src/server/main.rs:39-52``); the
    Python side keeps only per-peer metadata (status, chips). Falls back to
    a pure-Python clock map when the core is absent.
    """

    def __init__(self, *, prune_window_s: float = 10.0,
                 use_native: bool | None = None):
        self._lock = threading.Lock()
        self._peers: dict[str, Peer] = {}
        self.prune_window_s = prune_window_s
        self._native = None
        if use_native is None:
            use_native = native_core.available()
        if use_native:
            try:
                self._native = native_core.NativeRegistry(prune_window_s)
            except RuntimeError:
                self._native = None
        self.substrate = "native" if self._native is not None else "python"

    def touch(self, worker_id: str, *, chips: int | None = None,
              status: int | None = None) -> bool:
        """Refresh a peer; returns True if this is a new registration."""
        now = time.monotonic()
        with self._lock:
            if self._native is not None:
                is_new = self._native.touch(worker_id)
            else:
                is_new = worker_id not in self._peers
            peer = self._peers.setdefault(worker_id, Peer())
            peer.last_seen = now
            if chips is not None:
                peer.chips = chips
            if status is not None and peer.status != status:
                log.info("worker %s: %s -> %s", worker_id,
                         pb.WorkerStatus.Name(peer.status),
                         pb.WorkerStatus.Name(status))
                peer.status = status
        return is_new

    def prune(self) -> list[str]:
        """Drop peers silent for longer than the window; return their ids."""
        with self._lock:
            if self._native is not None:
                dead = self._native.prune()
            else:
                cutoff = time.monotonic() - self.prune_window_s
                dead = [wid for wid, p in self._peers.items()
                        if p.last_seen < cutoff]
            for wid in dead:
                self._peers.pop(wid, None)
        return dead

    def alive(self) -> int:
        with self._lock:
            if self._native is not None:
                return self._native.alive()
            return len(self._peers)


# ---------------------------------------------------------------------------
# The gRPC servicer + server lifecycle
# ---------------------------------------------------------------------------

def _scenario_fused_enabled() -> bool:
    """Twin of ``ops.fused.scenario_fused_enabled`` (the
    ``DBX_SCENARIO_FUSED`` kill switch), inlined so the dispatcher never
    imports the kernel (jax) module just to read an env flag. Read per
    RPC: flipping the switch stops NEW spec batches on the next poll."""
    return os.environ.get("DBX_SCENARIO_FUSED", "1") != "0"


class _PlacementGate:
    """One poll's live placement verdicts (``Dispatcher._placement_gate``):
    the admit closure plus the state it accumulates under the queue lock
    — per-job placement info for the decision records and outcome counts
    for the metrics — both drained by RequestJobs after take() returns."""

    __slots__ = ("admit", "info", "counts", "served_digests")

    def __init__(self):
        self.admit = None
        self.info: dict = {}
        self.counts = {"served": 0, "deferred": 0, "cap": 0}
        # Panel digests served THIS poll: the pending-digest refcount
        # only drops at lease commit (a later lock block), so without
        # this a chain child popped in the same batch as its parent
        # would still see the parent "pending" and burn a deferral.
        self.served_digests: set = set()


def _timed_rpc(method: str):
    """Record the handler's wall into ``dbx_rpc_seconds{method=...}``.

    The histogram child is pre-resolved in ``__init__``; ``obs.timer`` is
    the shared observe-on-exit contract (same one the worker-side RPC
    timings use) — ~1 µs per RPC, far inside the 2% budget on the ~16 ms
    batch-32 direct-dispatch RPC."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, request, context):
            with obs.timer(self._h_rpc[method]):
                return fn(self, request, context)
        return wrapper
    return deco


class Dispatcher(service.DispatcherServicer):
    """Wires the queue + registry behind the 5-RPC contract."""

    # In-memory DBXM blocks kept when no results_dir is configured. Beyond
    # this, the oldest block is evicted with a loud warning — an unbounded
    # dict would grow forever over a long fleet run (each block is
    # n_params x 9 float32s; 4096 blocks of a 2k-param grid ~ 300 MB).
    MAX_RESIDENT_RESULTS = 4096

    # Per-worker delivered-digest sets are bounded: past this many digests
    # the set is cleared (the worker merely re-receives full bytes once per
    # panel) instead of growing one entry per panel forever.
    MAX_DELIVERED_DIGESTS = 1 << 16

    # FetchCompiled payload bytes per reply: keeps one bulk fetch safely
    # under the channel's 256 MB message cap even when the fleet compile
    # store is full.
    COMPILED_REPLY_BUDGET = 64 * 1024 * 1024

    def __init__(self, queue: JobQueue, peers: PeerRegistry | None = None, *,
                 default_jobs_per_chip: int = 1,
                 results_dir: str | None = None,
                 registry: "obs.Registry | None" = None,
                 panel_dedupe: bool | None = None):
        self.queue = queue
        self.peers = peers or PeerRegistry()
        self.default_jobs_per_chip = default_jobs_per_chip
        # Dispatch by digest: send a panel's bytes to a worker generation
        # ONCE; every later job carrying the same digest ships digest-only
        # and the worker serves its cache (miss -> FetchPayload). The env
        # knob is read lazily per Dispatcher, not at import.
        if panel_dedupe is None:
            panel_dedupe = os.environ.get("DBX_PANEL_DEDUPE", "1") != "0"
        self.panel_dedupe = panel_dedupe
        # worker_id -> digests this worker's CURRENT registration has been
        # sent in full. Reset when a worker (re-)registers — a restarted
        # worker starts with an empty cache and must never wedge on a
        # phantom hit; dropped when the peer is pruned.
        self._delivered: dict[str, set[str]] = {}
        self._delivered_lock = threading.Lock()
        self.results_dir = results_dir
        self.results: dict[str, bytes] = {}
        self.results_evicted = 0
        # Guards results insert+evict: completions run on the gRPC thread
        # pool, and the eviction loop's iterate+delete must not race a
        # concurrent insert.
        self._results_lock = threading.Lock()
        if results_dir:
            os.makedirs(results_dir, exist_ok=True)
        # Observability (DESIGN.md "Observability"): per-RPC latency
        # histograms pre-resolved here, queue/peer gauges refreshed by a
        # scrape-time collector (zero steady-state cost), maintenance
        # counters incremented by the server's prune/requeue loop.
        self.obs = registry or obs.get_registry()
        self._h_rpc = {
            m: self.obs.histogram("dbx_rpc_seconds",
                                  help="dispatcher RPC handler wall",
                                  method=m)
            for m in ("RequestJobs", "SendStatus", "CompleteJob",
                      "CompleteJobs", "GetStats", "FetchPayload",
                      "AppendBars", "FetchCompiled", "OfferCompiled",
                      "TriggerDump")}
        self._c_dispatched = self.obs.counter(
            "dbx_jobs_dispatched_total", help="jobs handed to workers")
        self._c_scn_coalesced = self.obs.counter(
            "dbx_scenario_specs_coalesced_total",
            help="scenario records dispatched as spec-batch members "
                 "(megakernel route) instead of materialized panels")
        self._c_completions = {
            o: self.obs.counter("dbx_completions_total",
                                help="completion outcomes recorded",
                                outcome=o)
            for o in ("new", "dup", "unknown")}
        self._c_pruned = self.obs.counter(
            "dbx_peers_pruned_total", help="workers pruned for silence")
        self._c_requeued_prune = self.obs.counter(
            "dbx_requeued_jobs_total",
            help="jobs re-queued by recovery", reason="peer_pruned")
        self._c_requeued_lease = self.obs.counter(
            "dbx_requeued_jobs_total",
            help="jobs re-queued by recovery", reason="lease_expired")
        # Dispatch-by-digest accounting: full vs digest-only payload legs
        # and the wire bytes digest-only dispatch did NOT ship (the panel
        # lengths of every deduped leg).
        self._c_payloads = {
            mode: self.obs.counter(
                "dbx_dispatch_payloads_total",
                help="payload legs dispatched, by transport mode",
                mode=mode)
            for mode in ("full", "digest_only")}
        self._c_bytes_saved = self.obs.counter(
            "dbx_dispatch_bytes_saved_total",
            help="payload bytes NOT shipped thanks to digest-only "
                 "dispatch")
        self._c_fetches = {
            outcome: self.obs.counter(
                "dbx_payload_fetches_total",
                help="FetchPayload requests served, by outcome",
                outcome=outcome)
            for outcome in ("hit", "gone")}
        # Streaming appends (AppendBars): accepted extensions vs the
        # reject reasons, plus the delta-only dispatch leg (an append job
        # shipped as ΔT bars because the polling worker holds the base).
        self._c_appends = {
            outcome: self.obs.counter(
                "dbx_stream_appends_total",
                help="AppendBars requests, by outcome",
                outcome=outcome)
            for outcome in ("extended", "base_missing", "bad_delta",
                            "base_len_mismatch", "unsupported_strategy")}
        self._c_payloads["delta"] = self.obs.counter(
            "dbx_dispatch_payloads_total",
            help="payload legs dispatched, by transport mode",
            mode="delta")
        # Multi-tenant serving obs (DESIGN.md "Multi-tenant serving"):
        # per-tenant queue-wait distribution + SLO burn counters, labeled
        # through the BOUNDED tenant-bucket map (sched.tenancy — the
        # dbxlint obs-cardinality sanctioned source), riding the existing
        # /metrics + /stats.json + GetStats obs_json surfaces. The SLO
        # threshold is read lazily per Dispatcher, never at import.
        self.tenant_slo_s = float(os.environ.get("DBX_TENANT_SLO_S", 60.0))
        # Buckets whose per-tenant gauges this dispatcher has emitted: a
        # fully idle tenant's scheduler state is pruned, so its bucket
        # vanishes from tenant_stats() — the NEXT scrape must zero the
        # gauges instead of freezing them at the last live value.
        # Bounded by the tenant-bucket cap.
        self._tenant_buckets_emitted: set[str] = set()
        # Substrate autotuner fleet registry (tune/, round 11): workers
        # push newly tuned entries on JobsRequest.schedule_json; the
        # deterministic merge keeps the union, and GetStats ships it back
        # so the Nth worker inherits the first worker's tuning. Persists
        # through DBX_SCHEDULE_DIR when set (restarts keep the fleet's
        # schedules without re-gossip).
        from .. import tune as tune_mod

        self.fleet_schedule = tune_mod.ScheduleRegistry.open_default(
            registry=self.obs, scope="fleet")
        # Fleet-shared compile cache: byte-bounded store of workers'
        # persistent-compile-cache entries (DBX_COMPILE_CACHE_MB), served
        # by FetchCompiled / fed by OfferCompiled. Entries are opaque —
        # the dispatcher never needs jax.
        self.compile_store = tune_mod.CompileStore(registry=self.obs)
        # Live signal fan-out (serve/, round 13): the subscription
        # registry + result cache + push fan-out behind the
        # server-streaming Subscribe RPC. In-memory only — restart
        # semantics are "streams terminate, clients re-subscribe against
        # the journal-replayed chain". Imported lazily like tune above:
        # serve sits on rpc.panel_store/rpc.wire, and a module-level
        # import here would cycle through the rpc package __init__.
        from .. import serve as serve_mod

        self._serve = serve_mod
        self.hub = serve_mod.SubscriptionHub(
            registry=self.obs, streamable=STREAMABLE_STRATEGIES)
        # Fleet telemetry plane (obs/fleet.py, round 15): worker frames
        # gossiped on JobsRequest.telemetry_json merge here under the
        # staleness bound; the rollup rides /fleet.json, GetStats
        # obs_json (dbx_fleet) and the `dbxtop` CLI — and is the
        # worker-state view ROADMAP item 3's placement scorer ranks.
        self.fleet = obs_fleet.FleetView(registry=self.obs)
        # Dispatch decision plane (obs/decisions.py, round 19): every
        # take() resolution becomes one bounded decision record — WFQ
        # pick context, payload route, fleet-view age — scored off the
        # hot path by the placement ranker against THIS fleet view.
        # DBX_DECISIONS=0 kills record assembly entirely.
        self.decisions = obs_decisions.DecisionPlane(
            fleet=self.fleet, registry=self.obs)
        # Live locality placement (round 20): arm the plane's score
        # table — rebuilt on its daemon tick from the fleet view, the
        # delivered-digest ground truth, and completion calibration —
        # and the take-path gate reads it lock-free per poll
        # (_placement_gate). DBX_PLACEMENT=0 at construction keeps the
        # plane in round-19 pure-shadow mode; the per-poll gate checks
        # the knob again, so flipping it later also works (table
        # refreshes are cheap and verdict-free). Placement state is
        # deliberately NOT journaled: locality evidence (delivered
        # sets, calibration) dies with the process, so restarts restart
        # locality cold and replay stays byte-identical.
        if sched_placement.enabled():
            self.decisions.attach_placement(self._delivered_snapshot)
        self._c_placement = {
            o: self.obs.counter(
                "dbx_placement_total",
                help="live placement verdicts at take time: served "
                     "(best here or no better worker), deferred (held "
                     "for a better-scored worker), cap (better worker "
                     "exists but the deferral budget is spent)",
                outcome=o)
            for o in ("served", "deferred", "cap")}
        # Thread-local: concurrent GetStats calls on the gRPC pool must
        # each lend their OWN snapshot to the collector, not race on one
        # shared slot.
        self._pending_stats = threading.local()
        # Per-instance collector key: a second Dispatcher in the same
        # process (bench harnesses, restart overlap) must not be clobbered
        # by the first one's removal. Removal is owned by close() —
        # DispatcherServer.stop() calls it; a serverless Dispatcher should
        # call it directly when done.
        self._collector_key = f"dispatcher-{id(self)}"
        self.obs.add_collector(self._collector_key, self._collect_gauges)
        # Flight recorder sources (obs/flight.py, round 17): everything
        # a bundle snapshots beyond the span ring. Keyed last-wins like
        # registry collectors — the live dispatcher owns the names, and
        # close() releases them. Each callable runs on the capture
        # thread and takes only its own scrape-path locks (the lockdep
        # gate's contract).
        self._flight_sources = (
            ("metrics", self.obs.render_prometheus),
            ("fleet", self.fleet.snapshot),
            ("queue", self.queue.stats),
            ("schedule", self.fleet_schedule.to_json),
            ("lockdep", _lockdep_report),
            ("decisions", self.decisions.snapshot),
        )
        for name, fn in self._flight_sources:
            obs_flight.add_source(name, fn)

    def close(self) -> None:
        """Unhook this dispatcher from the obs registry: one final gauge
        refresh, then remove the collector so a stopped dispatcher neither
        publishes stale queue gauges nor pins its JobQueue alive. Also
        closes the subscription hub — every live Subscribe stream's pull
        loop wakes, sees its subscription closed, and ends."""
        self.hub.close()
        try:
            self._collect_gauges(self.obs)
        except Exception:
            pass
        self.obs.remove_collector(self._collector_key)
        for name, _ in self._flight_sources:
            obs_flight.remove_source(name)
        self.decisions.close()

    def _collect_gauges(self, reg: "obs.Registry") -> None:
        """Scrape-time refresh of queue-depth / liveness gauges (one
        ``queue.stats()`` read per scrape, none between scrapes). GetStats
        injects its own fresh read via ``_pending_stats`` so one queue-lock
        crossing serves both its reply and this collector."""
        s = getattr(self._pending_stats, "s", None)
        if s is None:
            s = self.queue.stats()
        reg.gauge("dbx_queue_jobs", pool="pending").set(s["jobs_pending"])
        reg.gauge("dbx_queue_jobs", pool="leased").set(s["jobs_leased"])
        reg.gauge("dbx_queue_jobs", pool="completed").set(
            s["jobs_completed"])
        reg.gauge("dbx_queue_jobs", pool="requeued").set(s["jobs_requeued"])
        reg.gauge("dbx_queue_jobs", pool="failed").set(s["jobs_failed"])
        reg.gauge("dbx_backtests_per_sec",
                  help="completed combos/s since dispatcher start").set(
            s["backtests_per_sec"])
        reg.gauge("dbx_workers_alive").set(self.peers.alive())
        reg.gauge("dbx_results_evicted").set(self.results_evicted)
        # Per-tenant queue depth + quota charge, SUMMED per bucket (the
        # overflow bucket aggregates every tenant past the label cap —
        # a set per tenant would leave last-writer-wins garbage there).
        pend: collections.Counter = collections.Counter()
        infl: collections.Counter = collections.Counter()
        demoted: collections.Counter = collections.Counter()
        for t, ts in self.queue.tenant_stats().items():
            b = tenant_bucket(t)
            pend[b] += ts["pending"]
            infl[b] += ts["inflight_combos"]
            demoted[b] += ts["demoted"]
        for b in self._tenant_buckets_emitted - set(pend):
            # Pruned (fully idle) bucket: zero its gauges rather than
            # freezing them at the last live reading.
            pend[b] = 0
        self._tenant_buckets_emitted |= set(pend)
        for b in pend:
            # Own family, NOT extra labels on dbx_queue_jobs: a
            # PromQL sum over dbx_queue_jobs{pool="pending"} also
            # matches children with extra labels, so per-tenant series
            # under the same family would double-count the backlog.
            reg.gauge("dbx_tenant_queue_jobs",
                      help="pending jobs by tenant bucket",
                      tenant=b).set(pend[b])
            reg.gauge("dbx_tenant_inflight_combos",
                      help="leased combo charge by tenant bucket "
                           "(DBX_TENANT_QUOTA's unit)",
                      tenant=b).set(infl[b])
            reg.gauge("dbx_tenant_demotions",
                      help="WFQ pops that pushed this tenant bucket's "
                           "over-quota head behind other tenants",
                      tenant=b).set(demoted[b])
        ps = self.queue.panel_store.stats()
        reg.gauge("dbx_panel_store_bytes",
                  help="bytes resident in the content-addressed panel "
                       "store").set(ps["bytes"])
        reg.gauge("dbx_panel_store_panels",
                  help="distinct panels resident in the store").set(
            ps["panels"])
        reg.gauge("dbx_panel_store_evictions",
                  help="LRU evictions from the panel store").set(
            ps["evictions"])
        cs = self.compile_store.stats()
        reg.gauge("dbx_compile_store_bytes",
                  help="bytes resident in the fleet compile-cache "
                       "store").set(cs["bytes"])
        reg.gauge("dbx_compile_store_entries",
                  help="compile-cache entries resident in the fleet "
                       "store").set(cs["entries"])
        # Fleet telemetry gauges + straggler/SLO-burn counters (bounded
        # worker-bucket labels inside).
        self.fleet.collect(reg)

    def obs_summary(self) -> dict:
        """The extended-stats payload: registry summaries (histogram
        digests + counters/gauges) plus the tail of the completed-span
        ring under ``dbx_spans_recent``, as carried by GetStats
        ``obs_json`` — the same window ``/stats.json`` ships."""
        out = self.obs.summaries(prefix="dbx_")
        out["dbx_spans_recent"] = obs.recent_spans(
            obs.http.STATS_SPAN_WINDOW)
        # The merged fleet telemetry document (same shape as
        # /fleet.json) — so a GetStats client needs no second endpoint.
        # summaries() above already ran the registry collectors, whose
        # fleet.collect built a snapshot: reuse it instead of folding
        # the whole fleet a second time per GetStats.
        out["dbx_fleet"] = (self.fleet.collected_snapshot()
                            or self.fleet.snapshot())
        return out

    # -- dispatch-by-digest bookkeeping ------------------------------------

    def forget_worker(self, worker_id: str) -> None:
        """Drop a pruned worker's delivered-digest set (its next
        registration starts cacheless anyway) and its fleet-telemetry
        entry (silence already proved the worker gone)."""
        with self._delivered_lock:
            self._delivered.pop(worker_id, None)
        self.fleet.forget(worker_id)

    def _forget_digest(self, digest: str) -> None:
        """Erase every record of having delivered ``digest``: after an
        unservable FetchPayload the next dispatch must ship full bytes,
        never point at the phantom address again."""
        with self._delivered_lock:
            for s in self._delivered.values():
                s.discard(digest)

    def _payload_leg(self, delivered: set | None, digest: str,
                     payload: bytes) -> bytes:
        """One leg's wire bytes: empty (digest-only dispatch) when this
        worker generation already received the digest in full, the full
        bytes (marked delivered) otherwise. ``delivered`` is None when
        dedupe is disabled. Mutates the per-worker set without the
        delivered lock: the set is only ever replaced under the lock, and
        add/discard from concurrent RPCs of one worker are atomic under
        the GIL (worst case a panel ships in full twice)."""
        if not digest or not payload:
            return payload
        if delivered is not None and digest in delivered:
            self._c_payloads["digest_only"].inc()
            self._c_bytes_saved.inc(len(payload))
            return b""
        if delivered is not None:
            if len(delivered) >= self.MAX_DELIVERED_DIGESTS:
                delivered.clear()
            delivered.add(digest)
        self._c_payloads["full"].inc()
        return payload

    def _append_leg(self, delivered: set | None, rec: JobRecord,
                    payload: bytes) -> bytes:
        """An append job's ``ohlcv`` leg: EMPTY (delta-only dispatch — the
        worker splices ``JobSpec.append_delta`` onto its cached base) when
        this worker generation holds the base or the extended panel
        itself; the full extended bytes otherwise. Either way the
        extended digest is marked delivered so follow-on appends chain
        delta-only."""
        if delivered is None:
            self._c_payloads["full"].inc()
            return payload
        has_base = (rec.append_parent in delivered
                    or rec.panel_digest in delivered)
        if len(delivered) >= self.MAX_DELIVERED_DIGESTS:
            delivered.clear()
            has_base = False
        delivered.add(rec.panel_digest)
        if has_base:
            self._c_payloads["delta"].inc()
            self._c_bytes_saved.inc(max(len(payload)
                                        - len(rec.delta or b""), 0))
            return b""
        self._c_payloads["full"].inc()
        return payload

    def _delivered_snapshot(self) -> dict:
        """Per-worker delivered-digest sets for the placement table
        builder (``DecisionPlane.attach_placement``). A shallow copy:
        the SETS ride by reference — membership reads are GIL-atomic,
        and a racy read is at worst one poll stale, which is exactly
        the staleness the table itself has."""
        with self._delivered_lock:
            return dict(self._delivered)

    def _placement_gate(self, worker_id: str):
        """The take() placement stage for ONE poll (round 20, replacing
        the round-6 append-affinity special case): rank every popped
        candidate across the pre-computed score table and defer a job —
        up to ``DBX_PLACEMENT_DEFER_CAP`` polls — when a better-scored
        worker should serve it instead. Returns ``None`` (no admit hook
        at all, pure WFQ order) when the stage is killed
        (``DBX_PLACEMENT=0``) or no fresh table exists (empty fleet,
        cold start, wedged scorer — the degradation ladder's floor).

        The returned gate's ``admit`` runs under the queue lock: pure
        dict/math over the frozen table (the table build did every
        fleet fold off this path). Verdicts accumulate on the gate —
        ``info`` (per-job, for the decision records) and ``counts``
        (for the ``dbx_placement_total`` counters) — and are flushed
        by RequestJobs AFTER take() returns, so no metric locks are
        ever taken under the queue lock."""
        if not sched_placement.enabled():
            return None
        table = self.decisions.placement_table()
        if table is None or not table.workers:
            return None
        cap = sched_placement.defer_cap()
        gate = _PlacementGate()
        # Chain-settling input, captured by reference: admit runs under
        # the queue lock, where these counts are mutated — a membership
        # read here can never tear.
        pending = self.queue._pending_digests

        def admit(rec: JobRecord) -> bool:
            try:
                ctx = obs_decisions.placement_ctx(rec)
                mine, best_wid, best = table.rank(ctx, worker_id)
            except Exception:
                # A scoring failure must never defer (or fail) a job.
                gate.counts["served"] += 1
                return True
            better = (best_wid != worker_id
                      and sched_placement.should_defer(
                          mine["cost_s"], best["cost_s"], 0, 1))
            # Chain settling: an append link whose parent job has not
            # dispatched yet scores holderless (equal costs everywhere,
            # `better` never fires) — wait for the parent to settle so
            # the table can route the whole chain, within the same
            # deferral budget. A parent served earlier in THIS poll
            # counts as settled (it is going to this very worker).
            base = str(ctx.get("base_digest") or "")
            wait_parent = (not better and bool(base)
                           and base not in gate.served_digests
                           and pending.get(base, 0) > 0
                           and sched_placement.should_wait_for_parent(
                               rec.affinity_skips, cap))
            if (better and rec.affinity_skips < cap) or wait_parent:
                rec.affinity_skips += 1
                gate.counts["deferred"] += 1
                return False
            gate.counts["cap" if better else "served"] += 1
            if rec.panel_digest:
                gate.served_digests.add(rec.panel_digest)
            gate.info[rec.id] = {
                "live": True,
                "best": best_wid,
                "cost_s": round(mine["cost_s"], 9),
                "best_cost_s": round(best["cost_s"], 9),
                "gap_s": round(mine["cost_s"] - best["cost_s"], 9),
                "defers": int(rec.affinity_skips),
                "cap": cap,
                "outcome": "cap" if better else "served",
                "table_workers": len(table.workers),
            }
            return True

        gate.admit = admit
        return gate

    # -- RPC handlers ------------------------------------------------------

    @_timed_rpc("RequestJobs")
    def RequestJobs(self, request: pb.JobsRequest, context) -> pb.JobsReply:
        is_new = self.peers.touch(request.worker_id, chips=request.chips)
        if request.schedule_json:
            # Tuned-schedule gossip (up leg): merge this worker's new
            # entries into the fleet registry. Malformed payloads teach
            # nothing (skip-and-count inside) — never an RPC error.
            self.fleet_schedule.merge_json(request.schedule_json)
        if request.telemetry_json:
            # Fleet telemetry gossip: adopt this worker's frame into the
            # staleness-bounded view (malformed frames counted, never an
            # RPC error — the schedule-gossip contract).
            self.fleet.update(request.worker_id, request.telemetry_json)
        if is_new:
            log.info("new worker %s with %d chips",
                     request.worker_id, request.chips)
        with self._delivered_lock:
            if is_new:
                # A (re-)registering worker starts cacheless: a stale
                # delivered set would dispatch digest-only panels the new
                # process never saw (FetchPayload would recover, but the
                # reset keeps the common restart case on the fast path).
                self._delivered[request.worker_id] = set()
            # Capability-gated: only workers that declared they resolve
            # digest-only payloads (JobsRequest.accepts_digest_only) ever
            # get bytes withheld — an older worker binary (proto3 default
            # false) always receives full payloads and cannot wedge on an
            # empty ohlcv it has no FetchPayload to recover.
            delivered = (self._delivered.setdefault(request.worker_id,
                                                    set())
                         if (self.panel_dedupe
                             and request.accepts_digest_only) else None)
        per_chip = request.jobs_per_chip or self.default_jobs_per_chip
        n = max(request.chips, 1) * max(per_chip, 1)
        t_disp0 = time.time()
        # Scenario megakernel opt-in: only a worker that declared the
        # spec-batch capability (proto3 default false — old binaries
        # never see a batch shape) and only while the kill switch is up.
        spec_jids: dict[str, str] | None = (
            {} if (request.accepts_scenario_batch
                   and _scenario_fused_enabled()) else None)
        # Decision plane (round 19): collect the pick-time WFQ context
        # only while recording is armed AND the scoring budget has
        # tokens (decisions.want) — with DBX_DECISIONS=0 or the budget
        # spent, neither the explain hook nor the record tuples below
        # are ever built and this path is the kill-switch path.
        explain: dict | None = (
            {} if obs_decisions.enabled() and self.decisions.want()
            else None)
        dec_batch: list[dict] = []
        # Live placement stage (round 20): gate verdicts accumulate on
        # the gate object under the queue lock; counters flush AFTER
        # take() returns (no metric locks under the queue lock).
        gate = self._placement_gate(request.worker_id)
        taken = self.queue.take(n, request.worker_id,
                                admit=(gate.admit if gate is not None
                                       else None),
                                scenario_spec=spec_jids,
                                explain=explain)
        if gate is not None:
            for o, v in gate.counts.items():
                if v:
                    self._c_placement[o].inc(v)
        if taken:
            self._c_dispatched.inc(len(taken))
        reply = pb.JobsReply()
        now = time.time()
        # Spec-dispatch records coalesce by everything the fused launch
        # compiles against (base, family, grid, static generator shape,
        # cost basis, tenant) — one carrier JobSpec per group, K specs
        # inside. vol_scale/shock/seed ride per-spec (traced values).
        scn_batches: dict[tuple, list] = {}
        for rec, payload in taken:
            # Per-job trace stitching: close the queue-wait span (enqueue
            # -> this take) and open/close the dispatch span (take +
            # payload materialization); the dispatch span's id rides the
            # JobSpec so the worker's chain parents onto it. Both are
            # root-level spans of the job's trace.
            parent_sid = ""
            if rec.trace_id and rec.enqueue_ts:
                obs.emit_span("job.queue_wait", rec.enqueue_ts,
                              t_disp0 - rec.enqueue_ts,
                              trace_id=rec.trace_id, job=rec.id)
                parent_sid = obs.emit_span(
                    "job.dispatch", t_disp0, now - t_disp0,
                    trace_id=rec.trace_id, job=rec.id,
                    worker=request.worker_id)
            if rec.enqueue_ts:
                # Per-tenant fairness instrumentation: queue wait under
                # the bounded tenant-bucket label + the SLO burn pair
                # (ok/breach vs DBX_TENANT_SLO_S) — burn rate is
                # breach/(ok+breach) over any scrape window.
                tb = tenant_bucket(rec.tenant)
                wait_s = max(t_disp0 - rec.enqueue_ts, 0.0)
                self.obs.histogram(
                    "dbx_tenant_queue_wait_seconds",
                    help="queue wait (enqueue -> take) by tenant bucket",
                    tenant=tb).observe(wait_s)
                self.obs.counter(
                    "dbx_tenant_slo_queue_wait_total",
                    help="queue-wait SLO burn by tenant bucket "
                         "(threshold DBX_TENANT_SLO_S)",
                    tenant=tb,
                    outcome=("breach" if wait_s > self.tenant_slo_s
                             else "ok")).inc()
                # Fleet-wide multi-window burn feed (the same SLO, the
                # dbx_fleet_slo_burn_total{window} counters).
                breach = wait_s > self.tenant_slo_s
                self.fleet.observe_slo(breach)
                if breach:
                    # The breach IS the incident: black-box the queue +
                    # fleet state while the offending job's spans are
                    # still in the ring. Deduped by (kind, tenant
                    # bucket) — one SLO storm, one bundle.
                    obs_flight.trigger(
                        "slo_breach", subject=tb, job=rec.id,
                        wait_s=round(wait_s, 3),
                        slo_s=self.tenant_slo_s)
            if spec_jids and rec.id in spec_jids:
                if explain is not None:
                    # Deferred decision record (tuple; see
                    # DecisionPlane.submit): the dict view assembles on
                    # the plane's thread, never on this path.
                    dec_batch.append((rec, "scenario",
                                      spec_jids[rec.id], len(payload),
                                      explain.get(rec.id),
                                      gate.info.get(rec.id)
                                      if gate is not None else None))
                scn_batches.setdefault(
                    (spec_jids[rec.id], rec.strategy,
                     tuple(sorted(
                         (k, np.asarray(v, np.float32).tobytes())
                         for k, v in rec.grid.items())),
                     int(rec.scenario.get("n_bars", 0)),
                     int(rec.scenario.get("block", 0)),
                     int(rec.scenario.get("regimes", 0)),
                     float(rec.cost), int(rec.periods_per_year),
                     rec.tenant),
                    []).append((rec, payload, parent_sid))
                continue
            payload2 = rec.ohlcv2 or b""
            leg1 = (self._append_leg(delivered, rec, payload)
                    if rec.append_parent else
                    self._payload_leg(delivered, rec.panel_digest,
                                      payload))
            if explain is not None:
                # The route the payload leg ACTUALLY took, derived from
                # the leg bytes the counters above just classified.
                if rec.append_parent:
                    route = "delta" if not leg1 else "full"
                else:
                    route = ("digest_only" if payload and not leg1
                             else "full")
                dec_batch.append((rec, route, rec.panel_digest,
                                  len(payload), explain.get(rec.id),
                                  gate.info.get(rec.id)
                                  if gate is not None else None))
            reply.jobs.append(pb.JobSpec(
                id=rec.id, strategy=rec.strategy,
                ohlcv=leg1,
                grid=wire.grid_to_proto(rec.grid), cost=rec.cost,
                periods_per_year=rec.periods_per_year,
                ohlcv2=self._payload_leg(delivered, rec.panel_digest2,
                                         payload2),
                wf_train=rec.wf_train, wf_test=rec.wf_test,
                wf_metric=rec.wf_metric,
                top_k=rec.top_k, rank_metric=rec.rank_metric,
                best_returns=rec.best_returns,
                trace_id=rec.trace_id, parent_span_id=parent_sid,
                panel_digest=rec.panel_digest,
                panel_bytes_len=len(payload),
                panel_digest2=rec.panel_digest2,
                panel_bytes_len2=len(payload2),
                append_parent_digest=rec.append_parent,
                append_base_len=rec.append_base_len,
                append_delta=rec.delta or b"",
                tenant_id=rec.tenant,
                scenario=(pb.ScenarioSpec(
                    base_digest=str(rec.scenario.get("base", "")),
                    n_bars=int(rec.scenario.get("n_bars", 0)),
                    block=int(rec.scenario.get("block", 0)),
                    regimes=int(rec.scenario.get("regimes", 0)),
                    vol_scale=float(rec.scenario.get("vol_scale", 0.0)),
                    shock=float(rec.scenario.get("shock", 0.0)),
                    seed=int(rec.scenario.get("seed", 0)))
                    if rec.scenario else None)))
        if scn_batches:
            # Lazy: only spec-capable polls with scenario records taken
            # pay the scenarios (jax) import — the same processes that
            # would otherwise have paid a full generator run per record.
            from .. import scenarios as scenarios_mod

            for members in scn_batches.values():
                rec0, payload0, sid0 = members[0]
                base_d = spec_jids[rec0.id]
                spec = pb.JobSpec(
                    id=rec0.id, strategy=rec0.strategy,
                    ohlcv=self._payload_leg(delivered, base_d, payload0),
                    grid=wire.grid_to_proto(rec0.grid), cost=rec0.cost,
                    periods_per_year=rec0.periods_per_year,
                    trace_id=rec0.trace_id, parent_span_id=sid0,
                    panel_digest=base_d, panel_bytes_len=len(payload0),
                    tenant_id=rec0.tenant)
                for rec, _, _ in members:
                    # The EFFECTIVE seed derives dispatcher-side from the
                    # record's host-precision params — the float32 wire
                    # roundtrip of vol_scale/shock can never skew the
                    # hash the worker would otherwise recompute.
                    eff = scenarios_mod.scenario_seed(
                        base_d,
                        scenarios_mod.ScenarioParams.from_dict(
                            rec.scenario))
                    spec.scenario_batch.append(pb.ScenarioSpec(
                        base_digest=base_d,
                        n_bars=int(rec.scenario.get("n_bars", 0)),
                        block=int(rec.scenario.get("block", 0)),
                        regimes=int(rec.scenario.get("regimes", 0)),
                        vol_scale=float(
                            rec.scenario.get("vol_scale", 0.0)),
                        shock=float(rec.scenario.get("shock", 0.0)),
                        seed=scenarios_mod.seed_to_int64(eff),
                        id=rec.id, trace_id=rec.trace_id))
                self._c_scn_coalesced.inc(len(members))
                reply.jobs.append(spec)
        if dec_batch:
            # One small-lock append for the whole poll; scoring (fleet
            # snapshot, shadow ranking) happens on the plane's thread.
            self.decisions.submit(dec_batch, worker=request.worker_id,
                                  t_take=t_disp0)
        if taken:
            log.info("dispatched %d jobs to %s", len(taken), request.worker_id)
        return reply

    @_timed_rpc("SendStatus")
    def SendStatus(self, request: pb.StatusRequest, context) -> pb.Ack:
        self.peers.touch(request.worker_id, status=request.status)
        return pb.Ack(ok=True)

    def _record_result(self, jid: str, metrics: bytes) -> None:
        if self.results_dir:
            # Persist to disk only — keeping every DBXM block resident
            # would grow without bound over a long run.
            with open(os.path.join(self.results_dir,
                                   f"{jid}.dbxm"), "wb") as fh:
                fh.write(metrics)
        else:
            with self._results_lock:
                self.results[jid] = metrics
                while len(self.results) > self.MAX_RESIDENT_RESULTS:
                    evicted = next(iter(self.results))
                    del self.results[evicted]
                    if self.results_evicted == 0:
                        log.warning(
                            "in-memory results exceeded %d blocks; "
                            "evicting oldest (job %s). Pass "
                            "--results-dir to persist every result to "
                            "disk.",
                            self.MAX_RESIDENT_RESULTS, evicted)
                    self.results_evicted += 1

    def _close_job_trace(self, jid: str, worker_id: str) -> None:
        """Emit the job's end-to-end span (enqueue ts -> completion
        recorded) — the wall the timeline analyzer's per-stage critical
        path must account for. First completion only ("new"); dups would
        re-close an already-closed trace."""
        tid, ets = self.queue.job_trace(jid)
        if tid and ets:
            obs.emit_span("job", ets, time.time() - ets, trace_id=tid,
                          job=jid, worker=worker_id)

    def _complete_one(self, jid: str, worker_id: str, metrics: bytes,
                      elapsed_s: float) -> str:
        # Same persist-then-journal order as CompleteJobs (see there).
        outcome = self.queue.complete_batch([jid], worker_id,
                                            journal=False)[0]
        if outcome == "unknown":
            return outcome
        if outcome == "new" and obs_decisions.enabled():
            # Decision-plane spu calibration: the measured end-to-end
            # worker wall against the units the shadow scorer parked.
            self.decisions.observe_completion(worker_id, jid, elapsed_s)
        if metrics:
            self._record_result(jid, metrics)
        if outcome == "new":
            # Live fan-out BEFORE the e2e span closes (its `push` span
            # must land inside the job's attribution window); the hub
            # probe is lock-free for the zero-subscription fleet, and a
            # dup can never re-push (the advance index pops on first
            # completion).
            if metrics and self.hub.has_advances():
                self.hub.on_result(jid, metrics,
                                   trace_id=self.queue.job_trace(jid)[0])
            self._close_job_trace(jid, worker_id)
        log.info("job %s completed by %s in %.3fs", jid, worker_id, elapsed_s)
        if outcome == "new" or (outcome == "dup" and metrics):
            # Journal metric-carrying dups too: the redelivery of a
            # delivery whose block landed but whose journal append never
            # ran (same rationale as CompleteJobs).
            self.queue.journal_completions([jid], worker_id)
        return outcome

    @_timed_rpc("CompleteJob")
    def CompleteJob(self, request: pb.CompleteRequest, context) -> pb.Ack:
        self.peers.touch(request.worker_id)
        outcome = self._complete_one(request.id, request.worker_id,
                                     request.metrics, request.elapsed_s)
        self._c_completions[outcome].inc()
        if outcome == "unknown":
            return pb.Ack(ok=False, detail=f"unknown job {request.id}")
        return pb.Ack(ok=True)

    @_timed_rpc("CompleteJobs")
    def CompleteJobs(self, request: pb.CompleteBatch,
                     context) -> pb.CompleteBatchReply:
        """Batched completions: one round trip for a whole drained batch
        AND one state-machine crossing for the batch (queue.complete_batch;
        the per-item semantics are identical to CompleteJob and remain
        idempotent; see backtesting.proto for the motivation numbers)."""
        self.peers.touch(request.worker_id)
        reply = pb.CompleteBatchReply()
        items = list(request.items)
        # journal=False: persist every .dbxm block BEFORE the durable
        # 'complete' records land. The reverse order loses a whole
        # batch's results on a crash in between (journaled-complete jobs
        # are never re-dispatched); this order merely re-runs the batch.
        outcomes = self.queue.complete_batch(
            [item.id for item in items], request.worker_id, journal=False)
        journal_ids: list[str] = []
        record_errors: list[tuple[str, Exception]] = []
        dec_comps: list[tuple] | None = (
            [] if obs_decisions.enabled() else None)
        for item, outcome in zip(items, outcomes):
            if outcome == "unknown":
                reply.unknown_ids.append(item.id)
                continue
            if outcome == "new":
                if dec_comps is not None:
                    dec_comps.append(
                        (request.worker_id, item.id, item.elapsed_s))
                # Live fan-out first (see _complete_one): the pushed
                # block is the completion payload, valid regardless of
                # whether the persist below succeeds — a redelivered
                # batch is "dup" and cannot double-push.
                if item.metrics and self.hub.has_advances():
                    self.hub.on_result(
                        item.id, item.metrics,
                        trace_id=self.queue.job_trace(item.id)[0])
                # Close the e2e span NOW: the state machine just recorded
                # the completion, which is the trace's end regardless of
                # whether the result block persists below — a persist
                # failure redelivers the batch as "dup", which would
                # never close it.
                self._close_job_trace(item.id, request.worker_id)
            if item.metrics:
                try:
                    self._record_result(item.id, item.metrics)
                except OSError as e:
                    # One item's disk failure must not forfeit the
                    # durable records of the OTHER items whose blocks
                    # landed. Skip this item's journal record and error
                    # the RPC below so the worker redelivers the batch
                    # ("dup" redeliveries re-record + re-journal — the
                    # journal tolerates duplicate 'complete' records).
                    record_errors.append((item.id, e))
                    log.error("job %s: result block not persisted (%s); "
                              "batch will be redelivered", item.id, e)
                    continue
            # Journal dups too: a dup may be the redelivery of exactly
            # this case (completed in state, block recorded now, durable
            # record still missing).
            journal_ids.append(item.id)
            log.info("job %s completed by %s in %.3fs", item.id,
                     request.worker_id, item.elapsed_s)
            if outcome == "new":
                reply.accepted += 1
            # "dup" (a retried delivery the dispatcher already recorded) is
            # deliberately neither accepted nor unknown: the worker already
            # counted it on the attempt the dispatcher processed.
        if dec_comps:
            # One decision-plane lock crossing for the whole batch (spu
            # calibration input; see observe_completions).
            self.decisions.observe_completions(dec_comps)
        for outcome, n in collections.Counter(outcomes).items():
            self._c_completions[outcome].inc(n)
        self.queue.journal_completions(journal_ids, request.worker_id)
        if record_errors:
            raise RuntimeError(
                f"{len(record_errors)} result block(s) not persisted "
                f"(first: job {record_errors[0][0]}: "
                f"{record_errors[0][1]}); redeliver the batch")
        return reply

    @_timed_rpc("GetStats")
    def GetStats(self, request: pb.StatsRequest, context) -> pb.StatsReply:
        # Direct stats() read FIRST — a queue failure must surface as an
        # RPC error the client can see (the collector path swallows
        # exceptions). The snapshot is then lent to the gauge collector
        # via _pending_stats so the obs_summary() call below does not
        # cross the queue lock a second time.
        s = self.queue.stats()
        self._pending_stats.s = s
        try:
            obs_json = json.dumps(self.obs_summary(), default=str)
        finally:
            self._pending_stats.s = None
        return pb.StatsReply(workers_alive=self.peers.alive(),
                             substrate=self.queue.substrate,
                             obs_json=obs_json,
                             schedule_json=self.fleet_schedule.to_json(),
                             **{
            k: (int(v) if k != "backtests_per_sec" else v)
            for k, v in s.items()})

    @_timed_rpc("FetchPayload")
    def FetchPayload(self, request: pb.PayloadRequest,
                     context) -> pb.PayloadReply:
        """Panel-cache miss recovery: serve a digest's bytes from the blob
        store (lazy re-materialization behind it). An unservable digest
        returns an EMPTY payload and is erased from every delivered set,
        so the job's next dispatch ships full bytes — miss -> fetch ->
        full job, never a failed job."""
        self.peers.touch(request.worker_id)
        blob = self.queue.payload_for_digest(request.digest)
        if blob is None:
            self._forget_digest(request.digest)
            self._c_fetches["gone"].inc()
            log.warning(
                "FetchPayload %s from %s: digest not servable (store "
                "evicted and source gone); forgetting its deliveries",
                request.digest[:16], request.worker_id)
            return pb.PayloadReply(digest=request.digest)
        self._c_fetches["hit"].inc()
        return pb.PayloadReply(digest=request.digest, payload=blob)

    @_timed_rpc("AppendBars")
    def AppendBars(self, request: pb.AppendRequest,
                   context) -> pb.AppendReply:
        """Streaming live-bar ingest: extend a content-addressed panel by
        a ΔT-bar DBX1 slice, enqueue one repricing job on the extended
        panel (when the request carries a job template — an EMPTY
        strategy is a tick-only append: chain extension for the
        subscription tier with no template job), and schedule the live
        fan-out: exactly ONE O(ΔT) advance job per unique subscribed
        stream of this chain, each registered with the hub BEFORE it is
        enqueued so its completion cannot outrun the push index. A
        rejected append is an explicit ok=false reply with the reason —
        the caller re-syncs; nothing is enqueued and nothing fails
        dispatcher-side."""
        self.peers.touch(request.worker_id)
        t_tick = time.time()
        strategy = request.job.strategy
        grid = wire.grid_from_proto(request.job.grid)
        cost = request.job.cost
        ppy = request.job.periods_per_year or 252
        tenant = request.job.tenant_id or DEFAULT_TENANT
        if strategy and strategy not in STREAMABLE_STRATEGIES:
            outcome, ndig, new_len = "unsupported_strategy", "", 0
        else:
            outcome, ndig, new_len = self.queue.extend_chain(
                request.panel_digest, int(request.base_len),
                request.delta)
        self._c_appends[outcome].inc()
        if outcome != "extended":
            log.warning("AppendBars %s from %s rejected: %s",
                        request.panel_digest[:16], request.worker_id,
                        outcome)
            return pb.AppendReply(ok=False, detail=outcome,
                                  panel_digest=ndig, new_len=new_len)
        # The tick hook: one dict probe for the non-serving case; on a
        # subscribed chain, the plan names every unique live stream
        # whose advance the template job does not already cover.
        tmpl_key = (self._serve.stream_key(strategy, grid, cost, ppy)
                    if strategy else None)
        plan = self.hub.on_tick(request.panel_digest, ndig, new_len,
                                template_key=tmpl_key)
        recs: list[JobRecord] = []
        rec = None
        if strategy:
            rec = self.queue.make_append_record(
                ndig, strategy=strategy, grid=grid, cost=cost,
                periods_per_year=ppy, tenant=tenant)
            recs.append(rec)
            if plan is not None and plan.template_live:
                self.hub.register_advance(rec.id, plan.chain, tmpl_key,
                                          ndig, new_len, t_tick)
        if plan is not None:
            for spec in plan.advances:
                r = self.queue.make_append_record(
                    ndig, strategy=spec.strategy, grid=spec.grid,
                    cost=spec.cost, periods_per_year=spec.ppy,
                    tenant=spec.tenant)
                self.hub.register_advance(r.id, plan.chain, spec.key,
                                          ndig, new_len, t_tick)
                recs.append(r)
        if recs:
            self.queue.enqueue_many(recs)
        log.info("AppendBars %s -> %s (%d bars): %d job(s)%s",
                 request.panel_digest[:16], ndig[:16], new_len,
                 len(recs),
                 f", {len(plan.advances)} stream advance(s)"
                 if plan is not None else "")
        return pb.AppendReply(ok=True,
                              job_id=rec.id if rec is not None else "",
                              panel_digest=ndig, new_len=new_len)

    # NOT @_timed_rpc: a streaming handler's "latency" is its lifetime —
    # timing the generator's construction would record ~0 and timing the
    # stream would poison the RPC histogram with hours-long samples.
    # Delivery latency has its own instrument (dbx_tick_to_push_seconds).
    def Subscribe(self, request: pb.SubscribeRequest, context):
        """Live signal fan-out (serve/): register this connection's
        interests and stream result pushes until the client drops the
        call, the dispatcher shuts down, or the handler's context dies.
        Invalid interests (unstreamable strategy) abort the RPC with
        INVALID_ARGUMENT — a client bug, answered loudly. The generator
        parks on the subscription's wake-up event between pushes (its
        own dedicated stream slot, never a shared unary one — see
        service.py on sizing max_workers), holding no locks while it
        waits. Deliberately NOT registered in the peer registry:
        subscribers are readers, not workers — 10k dashboards must not
        inflate workers_alive or churn the prune loop (their liveness
        IS the stream; the hub's dbx_subscriptions gauge counts them)."""
        interests = [
            self._serve.StreamSpec(
                strategy=js.strategy,
                grid=wire.grid_from_proto(js.grid),
                cost=js.cost,
                ppy=js.periods_per_year or 252,
                tenant=request.tenant_id or DEFAULT_TENANT,
                digest=js.panel_digest)
            for js in request.interests]
        try:
            sub = self.hub.subscribe(request.subscriber_id,
                                     request.tenant_id or DEFAULT_TENANT,
                                     interests)
        except ValueError as e:
            if context is not None:
                import grpc

                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            raise
        log.info("subscriber %s: %d interest(s), tenant %s%s",
                 request.subscriber_id, len(interests),
                 request.tenant_id or DEFAULT_TENANT,
                 " (demoted: over DBX_TENANT_SUB_QUOTA)"
                 if sub.demoted else "")
        try:
            while not sub.closed and (context is None
                                      or context.is_active()):
                for item in sub.pull(timeout=0.25):
                    self.hub.observe_delivery(item)
                    yield pb.PushUpdate(
                        panel_digest=item.digest,
                        stream_key=item.key,
                        seq=item.seq,
                        metrics=item.metrics,
                        new_len=item.new_len,
                        tick_unix=item.tick_unix,
                        changed=item.changed,
                        dropped=item.dropped,
                        catch_up=item.catch_up)
        finally:
            self.hub.unsubscribe(sub)

    @_timed_rpc("FetchCompiled")
    def FetchCompiled(self, request: pb.CompiledRequest,
                      context) -> pb.CompiledReply:
        """Fleet compile-cache fetch: empty ``keys`` = the listing only
        (the cheap poll — known_keys, no payloads); otherwise the
        requested entries still resident. A missing key is simply absent
        from the reply — the worker compiles locally and offers the
        result, never a failed job."""
        self.peers.touch(request.worker_id)
        reply = pb.CompiledReply()
        if not request.keys:
            reply.known_keys.extend(self.compile_store.keys())
            return reply
        budget = self.COMPILED_REPLY_BUDGET
        for key in request.keys:
            if budget <= 0:
                # Reply size guard (the worker also chunks its key
                # lists): entries past the budget simply stay missing
                # and ride the worker's next sync tick.
                break
            v = self.compile_store.get(key)
            if v is not None:
                reply.entries.append(pb.CompiledEntry(
                    key=key, name=v[0], payload=v[1]))
                budget -= len(v[1])
        return reply

    @_timed_rpc("OfferCompiled")
    def OfferCompiled(self, request: pb.CompiledOffer,
                      context) -> pb.Ack:
        """Fleet compile-cache offer: adopt a worker's freshly compiled
        cache entries (byte-bounded LRU; oversized/duplicate entries are
        silently ignored)."""
        self.peers.touch(request.worker_id)
        n = 0
        for e in request.entries:
            if self.compile_store.offer(e.key, e.name, e.payload):
                n += 1
        if n:
            log.info("adopted %d compile-cache entries from %s",
                     n, request.worker_id)
        return pb.Ack(ok=True, detail=str(n))

    @_timed_rpc("TriggerDump")
    def TriggerDump(self, request: pb.DumpRequest,
                    context) -> pb.DumpReply:
        """Admin black-box capture: synchronous, dedupe-bypassing flight
        bundle (the operator asked; they get a bundle or the reason
        why not)."""
        path = obs_flight.capture_now(
            "admin", subject=request.subject,
            detail={"reason": request.reason} if request.reason else {})
        if path is None:
            return pb.DumpReply(
                ok=False,
                detail="no bundle (DBX_FLIGHT_DIR unset or unwritable)")
        return pb.DumpReply(ok=True, bundle=os.path.basename(path),
                            detail=path)


class DispatcherServer:
    """Owns the grpc.Server plus the prune/requeue maintenance thread.

    ``metrics_port`` (None = off, 0 = ephemeral) additionally serves the
    dispatcher's obs registry as Prometheus text on
    ``http://<host>:<metrics_port>/metrics`` (+ ``/stats.json``)."""

    def __init__(self, dispatcher: Dispatcher, *, bind: str = "[::]:50051",
                 prune_interval_s: float = 1.0, max_workers: int = 16,
                 metrics_port: int | None = None,
                 metrics_host: str = "0.0.0.0"):
        self.dispatcher = dispatcher
        self._grpc = None
        self._bind = bind
        self._prune_interval_s = prune_interval_s
        self._max_workers = max_workers
        self._metrics_port = metrics_port
        self._metrics_host = metrics_host
        self.metrics: obs.MetricsServer | None = None
        self._stop = threading.Event()
        self._maint: threading.Thread | None = None
        self.port: int | None = None

    def start(self) -> "DispatcherServer":
        import grpc

        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers),
            options=service.default_channel_options(),
            compression=grpc.Compression.Gzip)
        service.add_dispatcher_to_server(self.dispatcher, self._grpc)
        self.port = self._grpc.add_insecure_port(self._bind)
        if self.port == 0:
            raise RuntimeError(f"could not bind {self._bind}")
        self._grpc.start()
        if self._metrics_port is not None:
            self.metrics = obs.MetricsServer(
                self._metrics_port, registry=self.dispatcher.obs,
                bind=self._metrics_host,
                routes={
                    # The merged fleet telemetry document (obs/fleet.py;
                    # `dbxtop --url` scrapes this).
                    "/fleet.json": self.dispatcher.fleet.snapshot,
                    # The decision-plane tail + aggregate regret
                    # (obs/decisions.py; `dbxwhy --url` scrapes this).
                    "/decisions.json": self.dispatcher.decisions.snapshot,
                }).start()
        self._maint = threading.Thread(
            target=self._maintenance_loop, name="dbx-maint", daemon=True)
        self._maint.start()
        log.info("dispatcher serving on %s (port %d)", self._bind, self.port)
        return self

    def _maintenance_loop(self) -> None:
        # The reference runs this as a 100 ms hot loop cloning the peer map
        # (reference src/server/main.rs:41-52); an event-wait tick is enough.
        d = self.dispatcher
        while not self._stop.wait(self._prune_interval_s):
            for wid in d.peers.prune():
                held = d.queue.requeue_worker(wid)
                d._c_pruned.inc()
                d._c_requeued_prune.inc(len(held))
                d.forget_worker(wid)
                log.warning("pruned silent worker %s; requeued %d jobs",
                            wid, len(held))
            expired = d.queue.requeue_expired()
            if expired:
                d._c_requeued_lease.inc(len(expired))
                log.warning("requeued %d expired leases", len(expired))
                # A lease expiring means a worker went quiet mid-batch —
                # exactly the evidence the span ring is about to roll
                # over. Deduped by the first expired job id.
                obs_flight.trigger("requeue_expired",
                                   subject=str(expired[0]),
                                   jobs=len(expired),
                                   job=str(expired[0]))
            for wid in d.fleet.prune():
                # Telemetry-entry eviction rides the same maintenance
                # tick as peer pruning: flagged stale first (visible
                # decay), evicted past 3x the staleness bound.
                log.info("evicted stale fleet-telemetry entry for %s",
                         wid)
            # Straggler flags from the merged fleet view are flight
            # triggers too: dedupe by worker id keeps a persistently
            # slow worker at one bundle per dedupe window.
            try:
                snap = (d.fleet.collected_snapshot()
                        or d.fleet.snapshot())
                for wid, w in snap.get("workers", {}).items():
                    for s in w.get("stragglers", ()):
                        obs_flight.trigger("straggler", subject=wid,
                                           stage=s)
            except Exception:
                log.exception("straggler flight-trigger sweep failed")

    def stop(self, grace: float = 1.0) -> None:
        self._stop.set()
        if self._maint is not None:
            self._maint.join(timeout=5.0)
        if self.metrics is not None:
            self.metrics.stop()
            self.metrics = None
        if self._grpc is not None:
            self._grpc.stop(grace=grace).wait()
        # Unhook the dispatcher's obs collector (final refresh inside):
        # the Worker side does the same cleanup in run()'s finally.
        self.dispatcher.close()


# ---------------------------------------------------------------------------
# Job construction + CLI
# ---------------------------------------------------------------------------

def parse_grid(spec: str) -> dict[str, np.ndarray]:
    """``"fast=5:25,slow=30:130:5"`` -> axis dict (start:stop[:step] or CSV)."""
    grid: dict[str, np.ndarray] = {}
    if not spec:
        return grid
    for part in spec.split(","):
        name, _, rng = part.partition("=")
        if ":" in rng:
            pieces = [float(x) for x in rng.split(":")]
            start, stop = pieces[0], pieces[1]
            step = pieces[2] if len(pieces) > 2 else 1.0
            grid[name.strip()] = np.arange(start, stop, step, dtype=np.float32)
        else:
            grid[name.strip()] = np.asarray(
                [float(x) for x in rng.split(";")], np.float32)
    return grid


def jobs_from_paths(paths, strategy: str, grid, *, cost: float = 0.0,
                    periods_per_year: int = 252, wf_train: int = 0,
                    wf_test: int = 0, wf_metric: str = "", top_k: int = 0,
                    rank_metric: str = "", best_returns: bool = False,
                    paths2=None,
                    tenant: str = DEFAULT_TENANT) -> list[JobRecord]:
    """File-backed jobs; two-legged strategies pass ``paths2`` (leg x
    files, positionally matched with ``paths``). Payloads are read at
    dispatch time, so enqueue stays cheap and restarts re-read nothing."""
    if paths2 is not None and len(paths2) != len(paths):
        raise ValueError(
            f"paths/paths2 length mismatch: {len(paths)} vs {len(paths2)}")
    paths2 = paths2 if paths2 is not None else [None] * len(paths)
    return [JobRecord(id=str(uuid.uuid4()), strategy=strategy, grid=grid,
                      cost=cost, periods_per_year=periods_per_year, path=p,
                      path2=p2,
                      wf_train=wf_train, wf_test=wf_test, wf_metric=wf_metric,
                      top_k=top_k, rank_metric=rank_metric,
                      best_returns=best_returns, tenant=tenant)
            for p, p2 in zip(paths, paths2)]


def scenario_jobs(base_digest: str, n: int, strategy: str, grid, *,
                  params: dict | None = None, cost: float = 0.0,
                  periods_per_year: int = 252,
                  tenant: str = DEFAULT_TENANT) -> list[JobRecord]:
    """``n`` digest-seeded scenario-sweep jobs over one real base panel.

    Each job's spec is ``(base_digest, params, seed=i)`` — scenario ``i``
    of the diversity sweep — and carries NO payload: the panel
    materializes dispatcher-side through the panel store at first take
    (``JobQueue._materialize``'s scenario leg) and dispatches like any
    other content-addressed panel. ``base_digest`` must be servable on
    this dispatcher (some enqueued job carries the base panel, or the
    store holds it); an unservable base fails the scenario job loudly at
    take, never silently.

    ``params`` are :class:`~..scenarios.ScenarioParams` fields
    (``n_bars``/``block``/``regimes``/``vol_scale``/``shock``; ``seed``
    is the sweep offset added per job)."""
    if strategy == "pairs":
        # Same up-front rejection as every other intake path (--data2,
        # STREAMABLE_STRATEGIES): a scenario spec generates ONE panel,
        # so a two-legged job would dispatch with no leg 2 and the
        # whole sweep would complete loudly empty worker-side.
        raise ValueError("scenario_jobs supports single-asset "
                         "strategies only (a spec generates one panel; "
                         "pairs needs a second leg)")
    # Normalize to the FULL effective parameter set (generator defaults
    # applied) before anything is journaled or dispatched: the record,
    # the journal and the wire ScenarioSpec echo must all describe the
    # panel that actually generates — a sparse dict echoed with proto
    # zero-defaults would not re-derive the same digest. Imported
    # lazily: only processes that create scenario jobs pay the
    # generator (jax) import.
    from .. import scenarios as scenarios_mod

    p = dict(params or {})
    seed0 = int(p.pop("seed", 0))
    known = {f.name for f in dataclasses.fields(
        scenarios_mod.ScenarioParams)}
    unknown = set(p) - known
    if unknown:
        raise ValueError(f"unknown scenario params: {sorted(unknown)} "
                         f"(known: {sorted(known)})")
    full = scenarios_mod.ScenarioParams.from_dict(p).to_dict()
    out = []
    for i in range(n):
        scn = {"base": base_digest, **full, "seed": seed0 + i}
        out.append(JobRecord(
            id=str(uuid.uuid4()), strategy=strategy, grid=grid, cost=cost,
            periods_per_year=periods_per_year, scenario=scn,
            tenant=tenant))
    return out


def synthetic_jobs(n: int, n_bars: int, strategy: str, grid, *,
                   cost: float = 0.0, seed: int = 0, wf_train: int = 0,
                   wf_test: int = 0, wf_metric: str = "", top_k: int = 0,
                   rank_metric: str = "", best_returns: bool = False,
                   tenant: str = DEFAULT_TENANT) -> list[JobRecord]:
    """Inline synthetic-OHLCV jobs (benchmarks / demos without data files).

    ``strategy="pairs"`` jobs carry two legs (``ohlcv`` = y, ``ohlcv2`` = x).
    """
    two_legged = strategy == "pairs"
    batch = data_mod.synthetic_ohlcv(n * (2 if two_legged else 1), n_bars,
                                     seed=seed)
    out = []
    for i in range(n):
        series = type(batch)(*(np.asarray(f[i]) for f in batch))
        ohlcv2 = None
        if two_legged:
            leg_x = type(batch)(*(np.asarray(f[n + i]) for f in batch))
            ohlcv2 = data_mod.to_wire_bytes(leg_x)
        out.append(JobRecord(
            id=str(uuid.uuid4()), strategy=strategy, grid=grid, cost=cost,
            ohlcv=data_mod.to_wire_bytes(series), ohlcv2=ohlcv2,
            wf_train=wf_train, wf_test=wf_test, wf_metric=wf_metric,
            top_k=top_k, rank_metric=rank_metric,
            best_returns=best_returns, tenant=tenant))
    return out


def make_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="dbx dispatcher: serve backtest jobs to polling workers")
    ap.add_argument("--bind", default="[::]:50051")
    ap.add_argument("--data", default=None,
                    help="glob of OHLCV files (CSV or DBX1) to enqueue")
    ap.add_argument("--data2", default=None,
                    help="pairs only: glob of leg-x OHLCV files, matched "
                         "positionally (both globs sorted) with --data's "
                         "leg-y files")
    ap.add_argument("--synthetic", type=int, default=0,
                    help="enqueue N synthetic tickers instead of files")
    ap.add_argument("--bars", type=int, default=1260,
                    help="bars per synthetic ticker")
    ap.add_argument("--strategy", default="sma_crossover")
    ap.add_argument("--grid", default="fast=5:25,slow=30:130:5")
    ap.add_argument("--cost", type=float, default=0.0)
    ap.add_argument("--journal", default=None,
                    help="JSONL journal path (enables crash recovery)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics (+ /stats.json) on this "
                         "port (0 = ephemeral; omit to disable)")
    ap.add_argument("--metrics-host", default="0.0.0.0",
                    help="interface for the /metrics server (use 127.0.0.1 "
                         "to scope the scrape surface to this host)")
    ap.add_argument("--results-dir", default=None)
    ap.add_argument("--lease-s", type=float, default=60.0)
    ap.add_argument("--prune-window-s", type=float, default=10.0)
    ap.add_argument("--jobs-per-chip", type=int, default=1)
    ap.add_argument("--wf-train", type=int, default=0,
                    help="walk-forward mode: train bars per refit window "
                         "(0 = plain sweep)")
    ap.add_argument("--wf-test", type=int, default=0,
                    help="walk-forward mode: out-of-sample bars per window")
    ap.add_argument("--wf-metric", default="sharpe",
                    help="walk-forward selection metric")
    ap.add_argument("--top-k", type=int, default=0,
                    help="workers reduce results on-device to the top-k "
                         "param rows (0 = ship the full per-combo matrix)")
    ap.add_argument("--rank-metric", default="sharpe",
                    help="ranking metric for --top-k / --best-returns")
    ap.add_argument("--tenant", default=DEFAULT_TENANT,
                    help="tenant identity stamped on every enqueued job "
                         "(weighted fair queueing; weights/quotas via "
                         "DBX_TENANT_WEIGHTS / DBX_TENANT_QUOTA)")
    ap.add_argument("--best-returns", action="store_true",
                    help="fleet-portfolio mode: workers ship each job's "
                         "best combo (by --rank-metric) plus its net-return "
                         "series (DBXP block); compose the book afterwards "
                         "with `aggregate --portfolio`")
    return ap


def build_dispatcher(args) -> Dispatcher:
    """Queue construction + journal restore + restart-safe job intake.

    Restart discipline (rerunning the same command line after a crash must
    not re-dispatch finished work): file paths the journal already knows are
    skipped, and synthetic seed jobs are only created when the journal holds
    no jobs at all — otherwise the restored pending set IS the remaining
    synthetic workload (synthetic payloads are journaled inline).
    """
    if args.journal:
        # Compact BEFORE opening the appending journal handle (the rewrite
        # replaces the inode): terminal jobs' payload blobs are dropped so
        # replay cost stops growing across restarts. Progress is reported
        # in bytes — stripping payloads usually preserves the LINE count.
        size_before = (os.path.getsize(args.journal)
                       if os.path.exists(args.journal) else 0)
        Journal.compact(args.journal)
        size_after = (os.path.getsize(args.journal)
                      if os.path.exists(args.journal) else 0)
        if size_after < size_before:
            log.info("compacted journal %s: %d -> %d bytes", args.journal,
                     size_before, size_after)
    queue = JobQueue(Journal(args.journal), lease_s=args.lease_s)
    restored = queue.restore(args.journal) if args.journal else 0
    if restored:
        log.info("restored %d pending jobs from journal", restored)

    grid = parse_grid(args.grid)
    # Walk-forward fields travel together, gated on --wf-train: a stray
    # --wf-test without --wf-train must not silently stamp inert fields on
    # records (they would split worker co-batching across a restart).
    if args.wf_train:
        wf_kw = dict(wf_train=args.wf_train, wf_test=args.wf_test,
                     wf_metric=args.wf_metric)
    else:
        if args.wf_test:
            log.warning("--wf-test %d ignored: walk-forward mode needs "
                        "--wf-train > 0", args.wf_test)
        wf_kw = dict(wf_train=0, wf_test=0, wf_metric="")
    wf_kw["tenant"] = args.tenant or DEFAULT_TENANT
    if args.top_k or args.best_returns:
        from ..ops.metrics import Metrics

        if args.rank_metric not in Metrics._fields:
            raise SystemExit(
                f"--rank-metric {args.rank_metric!r} unknown; one of "
                f"{', '.join(Metrics._fields)}")
    if args.top_k:
        if args.top_k < 0:
            raise SystemExit(f"--top-k {args.top_k} must be positive "
                             "(0 disables the reduction)")
        if args.wf_train:
            raise SystemExit("--top-k is a sweep-mode option; walk-forward "
                             "jobs already complete with one stitched OOS "
                             "row (drop --top-k or --wf-train)")
        wf_kw.update(top_k=args.top_k, rank_metric=args.rank_metric)
    if args.best_returns:
        if args.wf_train:
            raise SystemExit("--best-returns is a sweep-mode option; "
                             "walk-forward jobs have no single best combo "
                             "(drop --best-returns or --wf-train)")
        if args.top_k:
            raise SystemExit("--best-returns and --top-k are mutually "
                             "exclusive completion payloads (DBXP vs DBXS)")
        if args.strategy == "pairs":
            raise SystemExit("--best-returns supports single-asset "
                             "strategies only (the spread book needs both "
                             "legs' series)")
        wf_kw.update(best_returns=True, rank_metric=args.rank_metric)
    if args.data and args.strategy == "pairs" and not args.data2:
        raise SystemExit(
            "--strategy pairs with --data needs --data2: file-backed pairs "
            "jobs take leg-y files from --data and leg-x files from "
            "--data2, matched positionally (both globs sorted)")
    if args.data2 and args.strategy != "pairs":
        raise SystemExit("--data2 is pairs-only (two-legged jobs); "
                         f"--strategy is {args.strategy!r}")
    if args.data2 and not args.data:
        raise SystemExit("--data2 without --data: leg-y files are missing")
    if args.data:
        paths = sorted(glob_mod.glob(args.data))
        paths2 = sorted(glob_mod.glob(args.data2)) if args.data2 else None
        # Restart dedupe keys on the leg-y path (a pair is identified by
        # its y file). The journal — not sort position — is the authority
        # on which x file a journaled y was paired with: if the y-glob set
        # churns between runs with EQUAL counts (one y deleted, one added),
        # positional pairing would silently re-assign x legs that belong to
        # already-journaled pairs (advisor finding). New y files therefore
        # pair with the x files no journaled pair has claimed.
        path_set = set(paths)
        new_paths = [p for p in paths if p not in queue.known_paths]
        new_paths2 = None
        if paths2 is not None:
            gone_ys = {y for y in queue.known_pairings
                       if y not in path_set}
            if gone_ys and new_paths:
                # The churn signature (journaled ys vanished AND new ys
                # appeared) is exactly when positional pairing would have
                # silently re-assigned x legs; routine additions (no ys
                # gone) must not cry wolf.
                log.warning(
                    "pairs glob churn: %d journaled leg-y files no longer "
                    "match --data while %d new leg-y files appeared; "
                    "journaled pairings are kept and new files pair with "
                    "unclaimed leg-x files", len(gone_ys), len(new_paths))
            claimed_x = set(queue.known_pairings.values())
            unclaimed_x = [x for x in paths2 if x not in claimed_x]
            if new_paths and len(unclaimed_x) != len(new_paths):
                # Only fatal when something NEW would be enqueued with an
                # ambiguous pairing: on a pure crash-restart (every pair
                # already journaled) a since-vanished leg file must not
                # block serving the restored queue — restartability first.
                raise SystemExit(
                    f"--data matched {len(new_paths)} new leg-y files but "
                    f"--data2 has {len(unclaimed_x)} unclaimed leg-x files "
                    f"({len(paths2)} matched, "
                    f"{len(paths2) - len(unclaimed_x)} already paired in "
                    "the journal); pairs need one leg-x file per leg-y "
                    "file")
            if not new_paths and unclaimed_x:
                # Pure crash-restart with a stray unclaimed x file: nothing
                # new needs a pairing, so the restored queue is served and
                # the stray leg is merely noted (restartability first).
                log.info("ignoring %d unclaimed --data2 leg-x files: no "
                         "new leg-y files to pair them with", len(unclaimed_x))
            new_paths2 = unclaimed_x if new_paths else []
        if len(new_paths) < len(paths):
            log.info("skipping %d already-journaled paths",
                     len(paths) - len(new_paths))
        for rec in jobs_from_paths(new_paths, args.strategy, grid,
                                   cost=args.cost, paths2=new_paths2,
                                   **wf_kw):
            queue.enqueue(rec)
        log.info("enqueued %d file jobs", len(new_paths))
    if args.synthetic:
        if queue.journaled_jobs:
            log.info("journal already holds %d jobs; not re-seeding "
                     "%d synthetic jobs", queue.journaled_jobs,
                     args.synthetic)
        else:
            for rec in synthetic_jobs(args.synthetic, args.bars,
                                      args.strategy, grid, cost=args.cost,
                                      **wf_kw):
                queue.enqueue(rec)
            log.info("enqueued %d synthetic jobs", args.synthetic)

    results_dir = args.results_dir
    if not results_dir:
        # Spill by default: an in-memory-only dispatcher run would cap (and
        # then drop) results after MAX_RESIDENT_RESULTS blocks.
        import tempfile

        results_dir = tempfile.mkdtemp(prefix="dbx-results-")
        log.warning("no --results-dir given; persisting DBXM results to %s "
                    "(aggregate them with python -m "
                    "distributed_backtesting_exploration_tpu.rpc.aggregate)",
                    results_dir)
    return Dispatcher(
        queue, PeerRegistry(prune_window_s=args.prune_window_s),
        default_jobs_per_chip=args.jobs_per_chip,
        results_dir=results_dir)


def main(argv=None) -> None:
    import signal

    args = make_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    # Runtime lockdep (DBX_LOCKDEP=1): install BEFORE the queue/stores
    # are built so every package lock created below is instrumented.
    from ..analysis import lockdep

    lockdep.maybe_install()
    if os.environ.get("DBX_COMPILE_CACHE_DIR"):
        # Operator opted the dispatcher host into the persistent compile
        # cache (a dispatcher that also runs local jax work — bench, a
        # colocated worker). Best-effort; gated on the env knob because
        # importing jax is heavyweight for a pure control-plane process.
        from .. import tune as tune_mod

        tune_mod.configure()
    dispatcher = build_dispatcher(args)
    queue = dispatcher.queue
    server = DispatcherServer(dispatcher, bind=args.bind,
                              metrics_port=args.metrics_port,
                              metrics_host=args.metrics_host).start()
    # Graceful shutdown on SIGTERM too (k8s/systemd stop), not just ^C —
    # the journal is append-only so either way nothing is lost, but a clean
    # stop flushes in-flight RPCs (the reference had no shutdown path at
    # all; its own limitations list, reference README.md:75-88).
    stopping = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stopping.set())
    # SIGUSR2 = operator-requested black-box capture (the signal twin of
    # the TriggerDump RPC). The handler only enqueues — capture runs on
    # the recorder's own thread, never in signal context.
    if hasattr(signal, "SIGUSR2"):
        signal.signal(signal.SIGUSR2,
                      lambda *_: obs_flight.trigger("signal",
                                                    subject="SIGUSR2"))
    try:
        while not stopping.wait(timeout=5):
            log.info("stats: %s", queue.stats())
    except KeyboardInterrupt:
        pass
    log.info("shutting down")
    server.stop()


if __name__ == "__main__":
    main()
