"""Distributed control plane: the dispatcher <-> worker gRPC contract.

``backtesting.proto`` is the single source of truth for the wire contract
(same discipline as the reference, reference ``README.md:17``); generated
messages live in ``backtesting_pb2``, the hand-written stubs in
:mod:`.service`. :mod:`.dispatcher` is the server (leased durable queue,
peer liveness, stats); :mod:`.worker` the polling client; :mod:`.compute`
the backend seam where the JAX engine plugs in; :mod:`.journal` the
crash-recovery log; :mod:`.wire` the binary result codec;
:mod:`.page_pool` the device page pool behind ragged paged batching
(the worker panel cache's third level).

Run them:

    python -m distributed_backtesting_exploration_tpu.rpc.dispatcher \
        --synthetic 64 --grid "fast=5:25,slow=30:130:5" --journal q.jsonl
    python -m distributed_backtesting_exploration_tpu.rpc.worker \
        --connect localhost:50051 --backend jax
"""

from . import backtesting_pb2, compute, dispatcher, journal, service, wire, worker  # noqa: F401
