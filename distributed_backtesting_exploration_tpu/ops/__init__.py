"""Core TPU compute ops: rolling indicators, PnL engines, performance metrics.

These are the building blocks that replace the reference's compute stub
(reference ``src/worker/process.rs:21-25`` — a serial sleep loop). Everything
here is pure JAX, static-shaped, and safe under ``jit``/``vmap``/``shard_map``.
The time axis is always the **last** axis so that it maps onto TPU lanes.
"""

from .rolling import (  # noqa: F401
    rolling_sum,
    rolling_mean,
    rolling_std,
    rolling_var,
    rolling_ols,
    rolling_zscore,
    ema,
    rolling_max,
    rolling_min,
    valid_mask,
)
from .pnl import (  # noqa: F401
    simple_returns,
    log_returns,
    backtest_prefix,
    backtest_scan,
    BacktestResult,
)
from .metrics import (  # noqa: F401
    sharpe,
    sortino,
    max_drawdown,
    total_return,
    cagr,
    hit_rate,
    n_trades,
    summary_metrics,
    metric_sign,
    LOWER_IS_BETTER,
    Metrics,
)
from .signals import band_hysteresis  # noqa: F401
from .fused import (  # noqa: F401
    fused_sma_sweep,
    fused_bollinger_sweep,
    fused_bollinger_touch_sweep,
    fused_momentum_sweep,
    fused_donchian_sweep,
    fused_donchian_hl_sweep,
    fused_vwap_sweep,
    fused_rsi_sweep,
    fused_stochastic_sweep,
    fused_keltner_sweep,
    fused_macd_sweep,
    fused_pairs_sweep,
)
