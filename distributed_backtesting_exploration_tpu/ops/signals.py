"""Shared signal state machines (scan bodies used by multiple strategies).

The band entry/exit hysteresis machine — enter when a z-score breaches an
entry band, hold until it re-crosses an exit band — is the core stateful
pattern of both Bollinger mean-reversion and the pairs trade. One
implementation lives here so the scan semantics (warmup zeroing, no
flip-through-zero, unroll) cannot drift between strategies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def band_hysteresis(z: Array, valid: Array, z_entry, z_exit=0.0, *,
                    unroll: int = 8) -> Array:
    """Positions from a z-score band machine; shapes ``(..., T)`` -> same.

    Enter long (+1) when ``z < -z_entry``, short (-1) when ``z > z_entry``;
    exit to flat when z re-crosses ``-z_exit`` (long) / ``z_exit`` (short).
    Position never flips sign without passing through flat. Bars with
    ``valid`` False force flat. ``z_entry``/``z_exit`` may be traced scalars
    (vmap over parameter grids).
    """
    valid = jnp.broadcast_to(valid, z.shape)

    def step(pos, inp):
        z_t, valid_t = inp
        entered = jnp.where(z_t < -z_entry, 1.0,
                            jnp.where(z_t > z_entry, -1.0, 0.0))
        exit_long = (pos > 0) & (z_t >= -z_exit)
        exit_short = (pos < 0) & (z_t <= z_exit)
        held = jnp.where(exit_long | exit_short, 0.0, pos)
        nxt = jnp.where(pos == 0, entered, held)
        nxt = jnp.where(valid_t, nxt, 0.0)
        return nxt, nxt

    xs = (jnp.moveaxis(z, -1, 0), jnp.moveaxis(valid, -1, 0))
    _, pos_t = jax.lax.scan(step, jnp.zeros(z.shape[:-1]), xs, unroll=unroll)
    return jnp.moveaxis(pos_t, 0, -1)
