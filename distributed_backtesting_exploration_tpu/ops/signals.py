"""Shared signal state machines (scan bodies used by multiple strategies).

The band entry/exit hysteresis machine — enter when a z-score breaches an
entry band, hold until it re-crosses an exit band — is the core stateful
pattern of both Bollinger mean-reversion and the pairs trade. One
implementation lives here so the scan semantics (warmup zeroing, no
flip-through-zero, unroll) cannot drift between strategies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def band_hysteresis(z: Array, valid: Array, z_entry, z_exit=0.0, *,
                    unroll: int = 8) -> Array:
    """Positions from a z-score band machine; shapes ``(..., T)`` -> same.

    Enter long (+1) when ``z < -z_entry``, short (-1) when ``z > z_entry``;
    exit to flat when z re-crosses ``-z_exit`` (long) / ``z_exit`` (short).
    Position never flips sign without passing through flat. Bars with
    ``valid`` False force flat. ``z_entry``/``z_exit`` may be traced scalars
    (vmap over parameter grids).

    Serial reference implementation (``lax.scan`` over bars). Production
    paths use :func:`band_hysteresis_assoc`, which computes the identical
    state sequence in O(log T) depth; this version is kept as the
    semantics-defining golden model.
    """
    valid = jnp.broadcast_to(valid, z.shape)

    def step(pos, inp):
        z_t, valid_t = inp
        entered = jnp.where(z_t < -z_entry, 1.0,
                            jnp.where(z_t > z_entry, -1.0, 0.0))
        exit_long = (pos > 0) & (z_t >= -z_exit)
        exit_short = (pos < 0) & (z_t <= z_exit)
        held = jnp.where(exit_long | exit_short, 0.0, pos)
        nxt = jnp.where(pos == 0, entered, held)
        nxt = jnp.where(valid_t, nxt, 0.0)
        return nxt, nxt

    xs = (jnp.moveaxis(z, -1, 0), jnp.moveaxis(valid, -1, 0))
    _, pos_t = jax.lax.scan(step, jnp.zeros(z.shape[:-1]), xs, unroll=unroll)
    return jnp.moveaxis(pos_t, 0, -1)


def band_transition_maps(z: Array, valid: Array, z_entry, z_exit=0.0):
    """Per-bar transition maps of the band machine, as three float arrays.

    The machine's state space is {-1, 0, +1}, so each bar's update is a
    function from 3 states to 3 states. ``(frm_m, frm_0, frm_p)`` give the
    next state when the previous state is -1 / 0 / +1 respectively. Function
    composition over these maps is associative — the basis for the log-depth
    evaluation in :func:`band_hysteresis_assoc` and the fused Pallas kernel.
    """
    valid = jnp.broadcast_to(valid, z.shape)
    entered = jnp.where(z < -z_entry, 1.0, jnp.where(z > z_entry, -1.0, 0.0))
    frm_m = jnp.where(z <= z_exit, 0.0, -1.0)     # short exits at z<=z_exit
    frm_p = jnp.where(z >= -z_exit, 0.0, 1.0)     # long exits at z>=-z_exit
    frm_0 = entered
    zero = jnp.zeros_like(z)
    return (jnp.where(valid, frm_m, zero), jnp.where(valid, frm_0, zero),
            jnp.where(valid, frm_p, zero))


def _compose_maps(earlier, later):
    """``later ∘ earlier`` on 3-state maps: route each component through
    ``later``'s table with two selects (values are exactly -1/0/+1)."""
    lm, l0, lp = later

    def apply(v):
        return jnp.where(v < 0, lm, jnp.where(v > 0, lp, l0))

    em, e0, ep = earlier
    return apply(em), apply(e0), apply(ep)


def _shift_last(x: Array, s: int, fill: float) -> Array:
    """``y[..., t] = x[..., t-s]`` with ``fill`` for ``t < s`` (static s)."""
    pad = jnp.full(x.shape[:-1] + (s,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-s]], axis=-1)


def prefix_compose_maps(maps):
    """Inclusive prefix composition of per-bar 3-state maps, last axis.

    A Hillis–Steele shift-doubling ladder (log2 T rounds), NOT
    ``lax.associative_scan``: composing these maps only *selects* among
    exact {-1, 0, +1} values — no arithmetic — so every association order
    yields the bit-identical prefix, and the ladder's flat pad/slice graph
    avoids ``associative_scan``'s deeply recursive lowering (which
    compiles ~30x slower at sweep shapes — the `_ema_rows` finding — and
    whose native compile segfaulted under memory pressure on the CPU
    test harness: a load-sensitive crash in ``backend_compile_and_load``
    observed twice at ``test_assoc_traced_params_vmap``). The in-kernel
    twin is ``fused._prefix_compose3`` (sublane axis).
    """
    pm, p0, pp = maps
    T = pm.shape[-1]
    span = 1
    while span < T:
        earlier = (_shift_last(pm, span, -1.0),
                   _shift_last(p0, span, 0.0),
                   _shift_last(pp, span, 1.0))   # identity map past the edge
        pm, p0, pp = _compose_maps(earlier, (pm, p0, pp))
        span *= 2
    return pm, p0, pp


def band_hysteresis_assoc(z: Array, valid: Array, z_entry, z_exit=0.0) -> Array:
    """:func:`band_hysteresis` in O(log T) depth via prefix composition.

    Produces the bit-identical position sequence (states are small integers
    in float32; every comparison sees the same inputs) without a serial
    ``lax.scan`` — on TPU the whole time axis evaluates as ~log2(T) fused
    VPU passes instead of T sequential steps. This is the production path
    for stateful strategies (Bollinger mean-reversion, pairs). See
    :func:`prefix_compose_maps` for why this is a shift-doubling ladder
    rather than ``lax.associative_scan``.
    """
    maps = band_transition_maps(z, valid, z_entry, z_exit)
    _, p0, _ = prefix_compose_maps(maps)
    return p0   # start state is flat: the 0-component is the position path
