"""Rolling-window indicators as O(T) cumulative-sum kernels.

The reference never implements any indicator math (its compute path is a sleep
stub, reference ``src/worker/process.rs:21-25``); its north-star replacement is
"indicator construction (rolling SMA/std, rolling OLS)" run as fused jit+vmap
kernels (``BASELINE.json`` north_star). This module is that indicator layer.

Design notes (TPU-first):

- **Time is the last axis.** Arrays are ``(..., T)`` so the bar-time axis lands
  on TPU lanes (128-wide) and every op below is a fused VPU elementwise pass.
- **O(T) via cumulative sums**, not O(T*W) via explicit windows: a rolling sum
  over window ``w`` is ``cs[t] - cs[t-w]`` on the inclusive prefix sum. The
  shifted read uses a clipped ``take`` so that ``w`` may be a *traced* scalar —
  this is what lets a parameter sweep ``vmap`` over thousands of window lengths
  without recompilation or dynamic shapes.
- **Numerical stability in f32**: variance via ``E[x^2] - E[x]^2`` on raw
  price levels (~1e2) catastrophically cancels in float32. All second-moment
  ops first subtract the per-series mean (a constant shift changes neither
  variance nor covariance); means are shifted back where needed.
- Warmup positions ``t < w-1`` are invalid. Ops return them filled with
  ``fill`` (default NaN) and :func:`valid_mask` gives the boolean mask; PnL
  code multiplies positions by the mask instead of branching.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

Array = jax.Array


def _shifted(cs: Array, w, *, fill=0.0) -> Array:
    """Return ``cs[..., t - w]`` along the last axis, ``fill`` where ``t < w``.

    ``w`` may be a Python int or a traced scalar. Implemented with a clipped
    gather so the shape stays static under ``vmap`` over ``w``.
    """
    T = cs.shape[-1]
    idx = jnp.arange(T) - jnp.asarray(w)
    gather_idx = jnp.clip(idx, 0, T - 1).astype(jnp.int32)
    taken = jnp.take(cs, gather_idx, axis=-1)
    return jnp.where(idx >= 0, taken, fill)


def valid_mask(T: int, window) -> Array:
    """Boolean ``(T,)`` mask: True where a ``window``-bar indicator is defined.

    Broadcasts against any ``(..., T)`` indicator array.
    """
    return jnp.arange(T) >= window - 1


def rolling_sum(x: Array, window, *, fill=jnp.nan) -> Array:
    """Rolling sum over the trailing ``window`` bars (inclusive), same length.

    ``out[..., t] = sum(x[..., t-window+1 : t+1])``; warmup -> ``fill``.
    """
    cs = jnp.cumsum(x, axis=-1)
    out = cs - _shifted(cs, window)
    return _mask_warmup(out, window, fill)


def _mask_warmup(out: Array, window, fill) -> Array:
    T = out.shape[-1]
    return jnp.where(valid_mask(T, window), out, fill)


def rolling_mean(x: Array, window, *, fill=jnp.nan) -> Array:
    """Rolling mean (SMA) over the trailing ``window`` bars."""
    return rolling_sum(x, window, fill=fill) / jnp.asarray(window, x.dtype)


def _centered(x: Array) -> Array:
    # Constant per-series shift: preserves variances/covariances, kills the
    # float32 cancellation between E[x^2] and E[x]^2 for price-level inputs.
    return x - jnp.mean(x, axis=-1, keepdims=True)


def rolling_var(x: Array, window, *, ddof: int = 0, fill=jnp.nan) -> Array:
    """Rolling population (ddof=0) or sample (ddof=1) variance."""
    xc = _centered(x)
    w = jnp.asarray(window, x.dtype)
    s1 = rolling_sum(xc, window, fill=jnp.nan)
    s2 = rolling_sum(xc * xc, window, fill=jnp.nan)
    var = (s2 - s1 * s1 / w) / (w - ddof)
    var = jnp.maximum(var, 0.0)  # clamp tiny negative f32 residue
    return _mask_warmup(var, window, fill)


def rolling_std(x: Array, window, *, ddof: int = 0, fill=jnp.nan) -> Array:
    """Rolling standard deviation."""
    return jnp.sqrt(rolling_var(x, window, ddof=ddof, fill=fill))


def rolling_zscore(x: Array, window, *, ddof: int = 0, eps=1e-12,
                   fill=jnp.nan) -> Array:
    """``(x - rolling_mean) / rolling_std`` — the Bollinger/pairs entry signal."""
    m = rolling_mean(x, window, fill=jnp.nan)
    s = rolling_std(x, window, ddof=ddof, fill=jnp.nan)
    z = (x - m) / (s + eps)
    return _mask_warmup(z, window, fill)


def rolling_ols(y: Array, x: Array, window, *, eps=1e-12, fill=jnp.nan):
    """Rolling ordinary least squares of ``y`` on ``x`` (with intercept).

    Closed form from windowed moments (all O(T) cumsum differences)::

        beta_t  = cov_w(x, y) / var_w(x)
        alpha_t = mean_w(y) - beta_t * mean_w(x)

    Returns ``(alpha, beta)``, each shaped like ``y``. This is the
    linear-regression kernel behind the pairs-trade config
    (``BASELINE.json`` configs[3]).
    """
    w = jnp.asarray(window, y.dtype)
    mx = jnp.mean(x, axis=-1, keepdims=True)
    my = jnp.mean(y, axis=-1, keepdims=True)
    xc, yc = x - mx, y - my

    sx = rolling_sum(xc, window, fill=jnp.nan)
    sy = rolling_sum(yc, window, fill=jnp.nan)
    sxx = rolling_sum(xc * xc, window, fill=jnp.nan)
    sxy = rolling_sum(xc * yc, window, fill=jnp.nan)

    cov = sxy - sx * sy / w
    var = jnp.maximum(sxx - sx * sx / w, 0.0)
    beta = cov / (var + eps)
    # Means of the *uncentered* series: mean_w(x) = sx/w + mx.
    alpha = (sy / w + my) - beta * (sx / w + mx)
    return _mask_warmup(alpha, window, fill), _mask_warmup(beta, window, fill)


def ema(x: Array, *, span=None, alpha=None, fill=None) -> Array:
    """Exponential moving average via a parallel (associative) scan.

    ``y[t] = (1-a) * y[t-1] + a * x[t]``, ``y[0] = x[0]``, with
    ``a = 2/(span+1)`` when ``span`` is given. A first-order linear recurrence
    is associative under ``(A2,B2) o (A1,B1) = (A1*A2, A2*B1 + B2)``, so XLA
    evaluates it in O(log T) depth on the VPU instead of a serial loop —
    the TPU-idiomatic replacement for a per-bar Python loop.

    ``span``/``alpha`` may be traced scalars (vmap over decay grids).
    """
    if (span is None) == (alpha is None):
        raise ValueError("pass exactly one of span= or alpha=")
    if alpha is None:
        alpha = 2.0 / (jnp.asarray(span, x.dtype) + 1.0)
    a = jnp.broadcast_to(jnp.asarray(1.0 - alpha, x.dtype), x.shape)
    b = x * alpha
    # y[0] = x[0] exactly: make the first element's recurrence y0 = 0*prev + x0.
    t0 = jnp.arange(x.shape[-1]) == 0
    a = jnp.where(t0, 0.0, a)
    b = jnp.where(t0, x, b)

    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 * a2, a2 * b1 + b2

    _, y = jax.lax.associative_scan(combine, (a, b), axis=-1)
    return y


def ema_ladder(x: Array, *, span=None, alpha=None) -> Array:
    """Same EMA recurrence as :func:`ema`, evaluated as a Hillis–Steele
    shift-doubling ladder instead of ``lax.associative_scan``.

    ~log2(T) elementwise passes built from pad-shifts, combining with the
    same ``(A2,B2) o (A1,B1) = (A1*A2, A2*B1 + B2)`` monoid. Two reasons to
    pick this over :func:`ema`:

    - it is the *exact rounding twin* of the fused kernels' in-kernel EMA
      (``ops.fused._ema_ladder`` / ``_ema_rows``), so a generic-path model
      built on it agrees with its fused kernel to the last knife edge
      (associative_scan's Blelloch-style recursion rounds differently at
      ~1e-7, which is enough to flip a ``sign(a - b)`` crossing);
    - XLA compiles the unrolled shift ladder far faster than the scan's
      deep slice graph (measured ~30x on the bench shape) at equal runtime.

    ``span``/``alpha`` may be traced scalars (vmap over decay grids).
    """
    if (span is None) == (alpha is None):
        raise ValueError("pass exactly one of span= or alpha=")
    if alpha is None:
        alpha = 2.0 / (jnp.asarray(span, x.dtype) + 1.0)
    T = x.shape[-1]
    t0 = jnp.arange(T) == 0
    a = jnp.broadcast_to(jnp.asarray(1.0 - alpha, x.dtype), x.shape)
    A = jnp.where(t0, 0.0, a)                 # y[0] = x[0] exactly
    B = jnp.where(t0, x, x * alpha)
    step = 1
    while step < T:
        # Shift the (A, B) pairs down the time axis, filling with the
        # monoid identity (A=1, B=0), and fold into the running prefix.
        Ae = jnp.concatenate(
            [jnp.ones_like(A[..., :step]), A[..., :-step]], axis=-1)
        Be = jnp.concatenate(
            [jnp.zeros_like(B[..., :step]), B[..., :-step]], axis=-1)
        A, B = Ae * A, A * Be + B
        step *= 2
    return B


def obv_series(close, volume):
    """Normalized on-balance volume, shape ``(..., T)``; ``obv[0] = 0``.

    ``obv[t] = sum_{s<=t} sign(close[s] - close[s-1]) * v[s]`` with
    ``v = volume / volume[..., :1]`` (zero-guarded). The first-bar
    normalization keeps the double accumulation (this cumsum, then a
    windowed mean of it) at O(1) magnitudes instead of raw-volume ~1e6
    scale; the traded quantity ``sign(obv - sma)`` is invariant under the
    scaling. This is the ONE definition both the generic model
    (``models.obv``) and the fused kernel prep evaluate — shared so the
    two paths stay rounding twins by construction.
    """
    v0 = volume[..., :1]
    v = volume / jnp.where(v0 == 0.0, 1.0, v0)
    step = jnp.sign(jnp.diff(close, axis=-1, prepend=close[..., :1])) * v
    return jnp.cumsum(step, axis=-1)


def _static_window(window, name: str) -> int:
    if not isinstance(window, (int,)):
        raise TypeError(
            f"{name} requires a static (Python int) window; got {type(window)}. "
            "Rolling extrema have no cumsum form — sweep windows with a Python "
            "loop / jnp.stack over static values instead of vmap."
        )
    return int(window)


def rolling_extrema_traced(x: Array, window, *, max_window: int,
                           mode: str = "max", fill=jnp.nan) -> Array:
    """Rolling max/min with a *traced* window, bounded by ``max_window``.

    Rolling extrema have no cumsum form, so a traced window cannot use the
    doubling trick (:func:`rolling_max`). Instead each output reduces a
    masked ``(T, max_window)`` windowed view — O(T * max_window) work, but
    fully vectorized and vmap-able over window grids. Use the static-window
    versions when the window is known at trace time.
    """
    if mode not in ("max", "min"):
        raise ValueError(f"mode must be 'max' or 'min', got {mode!r}")
    T = x.shape[-1]
    offs = jnp.arange(max_window)                       # 0 .. W-1 lags
    idx = jnp.arange(T)[:, None] - offs[None, :]        # (T, W)
    neutral = -jnp.inf if mode == "max" else jnp.inf
    views = jnp.take(x, jnp.clip(idx, 0, T - 1).astype(jnp.int32), axis=-1)
    ok = (idx >= 0) & (offs[None, :] < jnp.asarray(window))
    views = jnp.where(ok, views, neutral)
    out = jnp.max(views, axis=-1) if mode == "max" else jnp.min(views, axis=-1)
    # A traced window larger than the static bound cannot raise here — poison
    # the output instead of silently truncating the lookback.
    out = jnp.where(jnp.asarray(window) <= max_window, out, jnp.nan)
    return _mask_warmup(out, window, fill)


def rolling_max(x: Array, window, *, fill=jnp.nan) -> Array:
    """Rolling max over trailing ``window`` bars (static window).

    Doubling trick: O(T log W) fused elementwise maxes, no gather loops —
    the Donchian-channel building block.
    """
    w = _static_window(window, "rolling_max")
    out = x
    span = 1  # out[t] currently covers x[t-span+1 .. t]
    while span < w:
        step = min(span, w - span)
        out = jnp.maximum(out, _shifted(out, step, fill=-jnp.inf))
        span += step
    return _mask_warmup(out, w, fill)


def rolling_min(x: Array, window, *, fill=jnp.nan) -> Array:
    """Rolling min over trailing ``window`` bars (static window)."""
    w = _static_window(window, "rolling_min")
    out = x
    span = 1
    while span < w:
        step = min(span, w - span)
        out = jnp.minimum(out, _shifted(out, step, fill=jnp.inf))
        span += step
    return _mask_warmup(out, w, fill)
