"""Fused Pallas sweep kernel for SMA-crossover parameter grids.

The generic sweep path (``parallel.sweep``) lets XLA materialize every
``(ticker, param, T)`` intermediate in HBM — profiling on v5e shows the
sweep is bound by that traffic, spread evenly across indicator, PnL and
metric passes. This kernel keeps the entire working set of one
(ticker x 128-param) cell in VMEM and writes only the 9 metric scalars per
backtest back to HBM:

- **Distinct-window SMA table.** A (fast, slow) grid of P combos touches only
  ~``n_fast + n_slow`` distinct windows. The table ``(T, W)`` per ticker is
  built once with the standard O(T) cumsum kernels, then each lane *selects*
  its two rows inside the kernel with a one-hot matmul — turning a per-lane
  gather (slow on TPU) into an MXU contraction.
- **Time on sublanes, params on lanes.** Each cell works on ``(T_pad, 128)``
  f32 tiles; per-bar recurrences (equity cumsum, running peak for drawdown,
  the band machines' 3-state compose) run as a SINGLE sequential pass over
  T-blocks with carry state between blocks (O(T) work — see
  :func:`_equity_scan`), with the original full-T log-depth shift-op
  ladders kept as the ``"ladder"`` fallback substrate, entirely in VMEM.
- **Padding discipline.** Bars padded beyond ``T`` hold the last position and
  earn zero return, so every reduction matches the unpadded reference
  exactly; metric denominators use the static true ``T``.

Numerics match :func:`~..parallel.sweep.run_sweep` +
:func:`~.metrics.summary_metrics` to float32 tolerance (golden-tested).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .metrics import Metrics

_LANES = 128
_METRIC_ROWS = 16   # 9 metric rows padded up to a legal f32 sublane tile
_EPS = 1e-12


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# A 1024-lane block only qualifies while one (T_pad, lanes) f32 value
# stays under this budget — past it the sign kernels' live set (returns,
# sign, pos, equity, two ladder temps) presses v5e VMEM and Mosaic
# spills. 6 MiB admits the headline T_pad=1280 (5.2 MiB/array).
_WIDE_BLOCK_BYTES = 6 * 1024 * 1024


# ---------------------------------------------------------------------------
# Tuned-schedule consultation (tune/ round 11)
#
# Every substrate knob below resolves through the SAME four-step chain:
# explicit call arg > env knob > tuned schedule > hardcoded default. The
# tuned schedule is the autotuner's persisted winner for the group's
# (kernel family, shape-bucket) — activated HOST-side by the worker
# backend around one group submit (`tuned_schedule`, thread-local so
# concurrent submit threads cannot bleed schedules into each other), or
# process-wide for knobs that bind at construction time
# (`set_tuned_defaults`, e.g. the page pool's page size). Placing the
# schedule BELOW env keeps every existing test and operator override
# byte-identical: an env knob always beats a tuned schedule. Tuned values
# are validated like env values but NEVER raise — an invalid entry (a
# corrupt registry, a newer peer's schema) silently degrades to the
# hardcoded default, because tuning must never fail a job. All reads stay
# host-side resolve-helper territory (dbxlint trace-time-env): resolved
# values thread into the kernels as jit statics exactly like env knobs.
# ---------------------------------------------------------------------------

_TUNED_TLS = threading.local()
_TUNED_GLOBAL: dict = {}


def set_tuned_defaults(schedule: dict | None) -> None:
    """Install (or clear, with None) process-wide tuned substrate
    defaults — the construction-time consultation used for knobs that
    bind before any group is submitted (page pool sizing). Host-side."""
    _TUNED_GLOBAL.clear()
    if schedule:
        _TUNED_GLOBAL.update({str(k): str(v)
                              for k, v in schedule.items()})


@contextlib.contextmanager
def tuned_schedule(schedule: dict | None):
    """Activate a tuned substrate schedule for the calling thread. The
    worker backend wraps one group submit in this, so every resolver the
    wrappers call inside sees the group's tuned values (below env)."""
    prev = getattr(_TUNED_TLS, "schedule", None)
    _TUNED_TLS.schedule = dict(schedule) if schedule else None
    try:
        yield
    finally:
        _TUNED_TLS.schedule = prev


def _tuned_value(key: str):
    """The active tuned value for ``key`` (thread-local schedule first,
    then the process-wide defaults), or None."""
    sched = getattr(_TUNED_TLS, "schedule", None)
    if sched is not None and key in sched:
        return sched[key]
    return _TUNED_GLOBAL.get(key)


def tuned_schedule_active() -> dict:
    """The merged tuned schedule in effect on this thread (observability:
    the ``dbx_tuned_substrate_info`` surface reads this, never the
    registry directly, so it cannot report values the kernels did not
    serve)."""
    out = dict(_TUNED_GLOBAL)
    sched = getattr(_TUNED_TLS, "schedule", None)
    if sched:
        out.update(sched)
    return out


# The legal param-block widths (f32 lane multiples the kernels tile by).
# DBX_LANES_CAP must name one of these — an off-ladder value can satisfy
# no candidate, and the old fall-through then returned the FULL un-blocked
# P_pad: the opposite of a cap, blowing VMEM on headline sweeps (ADVICE.md).
_LANES_LADDER = (_LANES, 256, 512, 1024)


def resolve_lanes_cap() -> int:
    """Validated ``DBX_LANES_CAP`` override (0 = unset).

    Read ONCE per public sweep call, host-side, and threaded into the
    jitted kernels as the static ``lanes_env`` argument — part of the jit
    cache key, so changing it in-process recompiles at the new width
    instead of silently reusing the stale one (ADVICE.md; the in-process
    A/B measured nothing before this). Raises on values outside the
    {128, 256, 512, 1024} ladder rather than falling through to an
    unbounded block width.
    """
    raw = os.environ.get("DBX_LANES_CAP")
    if not raw:
        tuned = _tuned_value("lanes_cap")
        if tuned is not None:
            try:
                tv = int(tuned)
            except (TypeError, ValueError):
                tv = -1
            if tv == 0 or tv in _LANES_LADDER:
                return tv
        return 0   # invalid tuned value: degrade to unset, never raise
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"DBX_LANES_CAP={raw!r} is not an integer; expected one of "
            f"{_LANES_LADDER} (or 0/empty to disable)") from None
    if v == 0:
        return 0   # explicit disable, same as unset (the old sentinel)
    if v not in _LANES_LADDER:
        raise ValueError(
            f"DBX_LANES_CAP={v} is unusable: no kernel block ladder "
            f"candidate matches it (legal values: {_LANES_LADDER})")
    return v


def _widest_lanes(P_pad: int, cap: int, T_pad: int | None = None,
                  env_cap: int = 0) -> int:
    """Widest legal param-block width <= ``cap``: fewer, wider cells
    amortize per-cell fixed overhead (+16% measured at 512 on the SMA
    headline — bench.py roofline_stages). Sign kernels take 512; kernels
    holding a 3-state compose ladder cap at 256 (VMEM budget).

    1024 stays OFF the default ladder: the roofline stage twin (HBM-table
    SMA) measured +7% at 1024, but the SHIPPED inline kernels measured a
    wash-to-regression in the 3x interleaved on-chip A/B (median sma
    -0.6%, momentum -2.6%, obv -0.5%) — the scratch table build plus the
    wider live set spills what the stage twin keeps resident. ``env_cap``
    is the :func:`resolve_lanes_cap`-validated ``DBX_LANES_CAP`` override
    (replaces ``cap`` for sign-kernel-class calls, still VMEM-gated),
    passed in as a jit-static so the A/B recompiles per setting."""
    if env_cap and cap > 256:
        cap = env_cap
    for cand in (1024, 512, 256, _LANES):
        if cand > 512 and (T_pad is None
                           or T_pad * cand * 4 > _WIDE_BLOCK_BYTES):
            continue
        if cand <= cap and P_pad >= cand and P_pad % cand == 0:
            return cand
    return P_pad


def _const(a):
    """Concrete device array, safe to build *inside* a trace.

    The lru-cached grid setups convert their numpy tables once and reuse the
    device buffer across calls. When a sweep is first invoked under an outer
    trace (e.g. ``jit(shard_map(...))`` in the multi-chip worker backend), a
    plain ``jnp.asarray`` would produce a tracer and the cache would capture
    it — escaping the trace and poisoning every later call."""
    with jax.ensure_compile_time_eval():
        return jnp.asarray(a)


def _pad_last(close, T_pad: int):
    """Pad ``(N, T)`` closes to ``T_pad`` bars by repeating the final close.

    Load-bearing for the padding discipline shared by every kernel here: a
    repeated last close makes the pad bars' returns exactly zero, so held
    positions earn nothing and reductions over T_pad match T_real
    (see ``_metrics_pack``).
    """
    pad_t = T_pad - close.shape[1]
    if not pad_t:
        return close
    return jnp.concatenate(
        [close, jnp.repeat(close[:, -1:], pad_t, axis=1)], axis=1)


def _t_real_col(t_real, close):
    """Per-ticker real bar counts as an (N, 1) int32 column for the kernels'
    SMEM array, or None for uniform histories (the kernels then specialize
    on a static length — measured ~25% faster than the dynamic path on the
    headline sweep). Ragged callers pass the lengths from
    :func:`~..utils.data.pad_and_stack`."""
    if t_real is None:
        return None
    return jnp.asarray(t_real, jnp.int32).reshape(close.shape[0], 1)


def _rets3(close_p):
    """Per-bar simple returns of padded closes, shaped ``(N, T_pad, 1)`` for
    a (1, T_pad, 1) kernel block (broadcasts over param lanes); ``r[0] = 0``."""
    prev = jnp.concatenate([close_p[:, :1], close_p[:, :-1]], axis=1)
    return (close_p / prev - 1.0)[..., None]


def _shift_t(x, s: int, fill: float):
    """``y[..., t] = x[..., t-s]`` along the last axis, ``fill`` for t < s
    (static shift: slice+concat copies, no gather). A shift at or beyond
    the axis length yields all-fill — the same answer the clipped-gather
    ``rolling._shifted`` gives, so windows larger than the (padded)
    history stay graceful instead of producing a wrapped negative slice."""
    T = x.shape[-1]
    if s == 0:
        return x
    if s >= T:
        return jnp.full_like(x, fill)
    pad = jnp.full(x.shape[:-1] + (s,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :T - s]], axis=-1)


def _rot_lanes(x, w: int):
    """``y[..., t] = x[..., (t - w) mod T`` — static rotate along the lane
    (minor) axis, expressed as a two-slice concat Mosaic lowers to lane
    rotations. Used by the in-kernel table builders (callers mask the
    wrapped region before use)."""
    T = x.shape[-1]
    w = w % T
    if w == 0:
        return x
    return jnp.concatenate([x[..., T - w:], x[..., :T - w]], axis=-1)


def _shift_down(x, k: int, fill: float):
    """``y[t] = x[t-k]`` along axis 0 with ``fill`` for t < k (static k)."""
    pad = jnp.full((k,) + x.shape[1:], fill, x.dtype)
    return jnp.concatenate([pad, x[:-k]], axis=0)


def _cumsum0(x):
    """Inclusive prefix sum along axis 0 via a log-depth doubling ladder."""
    t = x.shape[0]
    shift = 1
    while shift < t:
        x = x + _shift_down(x, shift, 0.0)
        shift *= 2
    return x


def _cummax0(x):
    """Inclusive running max along axis 0 via the same doubling ladder."""
    t = x.shape[0]
    shift = 1
    while shift < t:
        x = jnp.maximum(x, _shift_down(x, shift, -jnp.inf))
        shift *= 2
    return x


# ---------------------------------------------------------------------------
# Single-pass carry-scan epilogue (the "scan" substrate)
#
# BENCH_r05's roofline_stages put 47.6% of the flagship SMA sweep in the
# shared metrics tail's two full-T shift ladders (equity cumsum + running-
# peak cummax: O(T log T) element-ops), and another ~55% of every band
# machine's tail in the 3-state compose ladder. All three recurrences are
# now evaluated as ONE sequential pass over T-blocks with carry state
# threaded between blocks — O(T log B) work for a fixed block B, i.e. O(T).
# The carries (cumulative return, running-max equity, band machine state)
# live in VMEM vregs across an unrolled static block loop; block bounds are
# compile-time constants, so every slice is a static sublane slice (the
# T-block analogue of the sequential-grid scratch the inline tables use,
# without re-tiling the signal stage). The ladder path survives verbatim as
# the "ladder" fallback substrate so parity and flip budgets verify
# substrate-vs-substrate (`DBX_EPILOGUE=ladder`, bench roofline A/B rows).
# ---------------------------------------------------------------------------

_EPILOGUE_DEFAULT = "scan"
_SCAN_BLOCK_DEFAULT = 8          # one f32 sublane tile per block step
_SCAN_MAX_BLOCKS = 256           # unroll bound: B doubles past this


def _epilogue_ok(epilogue: str) -> bool:
    if epilogue in ("ladder", "scan"):
        return True
    if isinstance(epilogue, str) and epilogue.startswith("scan:"):
        try:
            b = int(epilogue[5:])
        except ValueError:
            return False
        return b >= 8 and b % 8 == 0
    return False


def _resolve_epilogue(epilogue: str | None) -> str:
    """Shared epilogue-substrate knob: explicit arg > ``DBX_EPILOGUE`` >
    tuned schedule > ``"scan"``. ``"scan"`` (default) is the single-pass
    blocked carry scan; ``"scan:<B>"`` pins the T-block size to ``B``
    sublane rows (multiple of 8 — the tuning surface for the on-chip A/B
    and the autotuner's epilogue axis); ``"ladder"`` is the O(T log T)
    full-T shift-ladder fallback kept for substrate-vs-substrate
    verification. An invalid arg/env value raises (operator error); an
    invalid TUNED value silently degrades to the default (tuning must
    never fail a job)."""
    if epilogue is None:
        epilogue = os.environ.get("DBX_EPILOGUE")
        if epilogue is None:
            tuned = _tuned_value("epilogue")
            if tuned is not None and _epilogue_ok(tuned):
                return tuned
            epilogue = _EPILOGUE_DEFAULT
    if _epilogue_ok(epilogue):
        return epilogue
    raise ValueError(
        f"epilogue must be 'scan', 'scan:<B>' (B a positive multiple of 8) "
        f"or 'ladder', got {epilogue!r}")


def _scan_block(T_pad: int, epilogue: str) -> int:
    """Static T-block size for the carry scan. The default starts at one
    sublane tile (8 rows — the modeled sweet spot: per-row ladder work is
    4*log2(B), so smaller blocks do strictly less VPU work) and doubles
    until the unrolled block count fits ``_SCAN_MAX_BLOCKS`` (bounding
    Mosaic program size for long-context shapes)."""
    if epilogue.startswith("scan:"):
        return int(epilogue[5:])
    b = _SCAN_BLOCK_DEFAULT
    while -(-T_pad // b) > _SCAN_MAX_BLOCKS:
        b *= 2
    return b


def _spans(T_pad: int, block: int):
    """Static (start, stop) spans tiling the sublane axis by ``block``."""
    return [(s, min(s + block, T_pad)) for s in range(0, T_pad, block)]


def _interp_epilogue(epilogue: str, T_pad: int, interpret: bool) -> str:
    """Interpret mode (the CPU test path) re-blocks the default scan to
    ONE T-block: the long unrolled per-block op chain that is cheap for
    Mosaic is expensive for trace + XLA-CPU interpretation (measured ~8x
    golden-test wall at the default 8-row block vs ~1x single-block —
    a single block does the ladder's exact op count through the scan
    code path). Carry chains across block boundaries are exercised by
    the dedicated multi-block substrate tests (tests/test_z_epilogue.py),
    which pin ``"scan:<B>"`` explicitly; pinned values and ``"ladder"``
    pass through untouched. Block size only moves the f32 association
    rounding of the equity-path metrics."""
    if not interpret or epilogue != "scan":
        return epilogue
    return f"scan:{_round_up(T_pad, 8)}"


def _equity_scan(net, block: int):
    """``(mdd, eq_final)`` of ``equity = 1 + cumsum(net)`` in one
    sequential pass over T-blocks.

    Carries: the cumulative net return and the running-max equity, both
    ``(1, lanes)`` rows threaded between blocks. Per block the local
    cumsum/cummax ladders are log2(block)-deep instead of log2(T_pad) —
    total O(T log B) = O(T) for the static ``block``. Padding discipline
    (``net == 0`` for ``t >= tr``) makes masks unnecessary: equity and
    peak freeze at the last real bar, so pad rows' drawdown replays
    ``dd[tr-1]`` exactly and the final carry IS the total return. For a
    single block this is bit-identical to the ladder substrate
    (``x + 0.0 == x``); across blocks the summation tree differs by the
    usual f32 association rounding (~1 ULP class — positions, and hence
    every flip-sensitive comparison, are untouched). Since round 16
    this is no longer prose-only: dbxcert's association-boundary census
    counts every block-merge add and ladder step on the equity cone and
    pins the counts per substrate in ``numerics.contract.json`` — a
    re-blocking or reassociating edit here fails the drift gate with
    the introducing equation chain."""
    T_pad, lanes = net.shape
    carry = jnp.zeros((1, lanes), jnp.float32)
    peak_c = jnp.full((1, lanes), -jnp.inf, jnp.float32)
    mdd = jnp.zeros((1, lanes), jnp.float32)
    for s, e in _spans(T_pad, block):
        cs = _cumsum0(net[s:e])
        eq = (1.0 + carry) + cs
        peak = jnp.maximum(_cummax0(eq), peak_c)
        dd = (peak - eq) / jnp.maximum(peak, _EPS)
        mdd = jnp.maximum(mdd, jnp.max(dd, axis=0, keepdims=True))
        carry = carry + cs[e - s - 1:]
        peak_c = peak[e - s - 1:]
    return mdd[0], 1.0 + carry[0]


def _cumsum_last(x):
    """Inclusive prefix sum over the LAST axis as a Hillis–Steele
    shift-doubling ladder — the host-XLA twin of :func:`_cumsum0`.
    NOT ``jnp.cumsum``: its ``associative_scan`` lowering compiles a
    deeply recursive slice graph, and the blocked equity advance emits
    one prefix op PER BLOCK — hundreds of them at long-context shapes
    turned a tiny jit into a multi-minute XLA-CPU compile (the
    ``ema_ladder`` lesson, re-learned host-side)."""
    T = x.shape[-1]
    s = 1
    while s < T:
        pad = jnp.zeros(x.shape[:-1] + (s,), x.dtype)
        x = x + jnp.concatenate([pad, x[..., :-s]], axis=-1)
        s *= 2
    return x


def _cummax_last(x):
    """Inclusive running max over the LAST axis (shift ladder, see
    :func:`_cumsum_last`)."""
    T = x.shape[-1]
    s = 1
    while s < T:
        pad = jnp.full(x.shape[:-1] + (s,), -jnp.inf, x.dtype)
        x = jnp.maximum(x, jnp.concatenate([pad, x[..., :-s]], axis=-1))
        s *= 2
    return x


def _equity_advance(net, block: int, cum, peak, mdd):
    """Recurrent form of :func:`_equity_scan`, over the LAST axis.

    Advances the ``(cumulative net, running peak, max drawdown)`` carry
    across a ``(..., D)`` net-return slice in T-blocks of ``block`` bars
    — the exact carry threading `_equity_scan` uses between its blocks,
    exposed as a standalone step so a streaming append
    (``streaming.recurrent``) can continue a finished sweep's equity
    state in O(ΔT). Block boundaries are the only association
    difference vs a cold full-length scan (the PR-3 f32 budget);
    ``cum``/``peak``/``mdd`` initialize to ``0 / -inf / 0`` exactly as
    `_equity_scan` seeds them, so the scan form is literally one call
    covering the whole panel. This is a certified cone: every streaming
    family's build/append row in ``numerics.contract.json`` pins the
    census of the shift-ladder and block-merge adds emitted here (the
    structural reassociations dbxcert counts without any reduce
    primitive present)."""
    D = net.shape[-1]
    for s, e in _spans(D, block):
        cs = _cumsum_last(net[..., s:e])
        eq = (1.0 + cum)[..., None] + cs
        pk = jnp.maximum(_cummax_last(eq), peak[..., None])
        dd = (pk - eq) / jnp.maximum(pk, _EPS)
        mdd = jnp.maximum(mdd, jnp.max(dd, axis=-1))
        cum = cum + cs[..., -1]
        peak = pk[..., -1]
    return cum, peak, mdd


def _unpack_tr(refs, T_real):
    """Shared ragged-vs-uniform ref plumbing for all sweep kernels: with a
    static ``T_real`` the refs are just ``(out_ref,)``; in ragged mode an
    SMEM lengths array precedes it and this grid row's length is read out.
    Returns ``(tr, out_ref)``."""
    if T_real is None:
        tr_ref, out_ref = refs
        return tr_ref[pl.program_id(0), 0], out_ref
    (out_ref,) = refs
    return T_real, out_ref


def _tr_specs(T_real):
    """Extra in_specs for ragged mode (whole lengths array in SMEM)."""
    return [] if T_real is not None else [
        pl.BlockSpec(memory_space=pltpu.SMEM)]


def _tr_args(t_real, T_real):
    """Extra pallas operands for ragged mode."""
    return [] if T_real is not None else [t_real]


def _row_at(x, tr, t_idx, *, keepdims: bool):
    """Row ``tr - 1`` of a (T_pad, 128) tile. Static ``tr`` folds to a plain
    slice (zero runtime cost — the uniform-history fast path); a traced
    ``tr`` uses a one-hot masked sum, bit-identical to the slice (exactly
    one nonzero row) but one extra VPU pass."""
    if isinstance(tr, int):
        row = x[tr - 1:tr, :]
        return row if keepdims else row[0]
    return jnp.sum(jnp.where(t_idx == tr - 1, x, 0.0), axis=0,
                   keepdims=keepdims)


def _metrics_tail(pos, r, t_idx, tr, *, cost: float, ppy: int,
                  epilogue: str = _EPILOGUE_DEFAULT):
    """Shared kernel tail: positions -> packed (16, 128) metric rows.

    ``pos`` is the per-lane position path over ``(T_pad, 128)`` (any signal
    kernel produces it); ``tr`` is this ticker's real bar count (an int32
    scalar — traced, so ragged groups work with one compiled kernel). Bars
    at ``t >= tr`` are overwritten to hold the final real position so every
    reduction over T_pad equals the unpadded reduction over tr (zero
    return, zero turnover in the pad). ``epilogue`` picks the equity/
    drawdown substrate (see `_equity_scan` / `_resolve_epilogue`).
    """
    row_ok = t_idx < tr
    pos_last = _row_at(pos, tr, t_idx, keepdims=True)
    pos = jnp.where(row_ok, pos, pos_last)

    prev = _shift_down(pos, 1, 0.0)
    net = prev * r - cost * jnp.abs(pos - prev)
    return _metrics_pack(pos, prev, net, row_ok, t_idx, tr, ppy=ppy,
                         epilogue=epilogue)


def _metrics_pack(pos, prev, net, row_ok, t_idx, tr, *, ppy: int,
                  epilogue: str = _EPILOGUE_DEFAULT):
    """Reduce per-bar ``net``/positions to the packed (16, 128) metric rows.

    Callers guarantee the padding discipline: ``pos`` holds its final real
    value for ``t >= tr`` and ``net`` is exactly zero there, so plain
    reductions over T_pad equal the unpadded reductions over tr.
    """
    n = jnp.asarray(tr, jnp.float32)
    s1 = jnp.sum(net, axis=0)
    s2 = jnp.sum(net * net, axis=0)
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    std = jnp.sqrt(var)
    ann = jnp.sqrt(jnp.float32(ppy))
    down = jnp.minimum(net, 0.0)
    dstd = jnp.sqrt(jnp.sum(down * down, axis=0) / n)

    if epilogue == "ladder":
        equity = 1.0 + _cumsum0(net)
        peak = _cummax0(equity)
        dd = (peak - equity) / jnp.maximum(peak, _EPS)
        mdd = jnp.max(jnp.where(row_ok, dd, 0.0), axis=0)
        eq_final = _row_at(equity, tr, t_idx, keepdims=False)
    else:
        mdd, eq_final = _equity_scan(
            net, _scan_block(net.shape[0], epilogue))

    active = (jnp.abs(prev) > 0) & row_ok
    wins = (net > 0) & active
    hit = jnp.sum(wins.astype(jnp.float32), axis=0) / (
        jnp.sum(active.astype(jnp.float32), axis=0) + _EPS)

    turnover = jnp.sum(jnp.abs(pos - prev), axis=0)
    years = jnp.maximum(n / jnp.float32(ppy), _EPS)
    final = jnp.maximum(eq_final, _EPS)

    # Pack the 9 metrics onto sublanes of one (16, lanes) output tile — a
    # (1, lanes)-per-metric block shape is not a legal TPU tile. The lane
    # width comes from the position block (each launcher picks its widest
    # legal block: <=512 for fused-SMA, <=256 for the band machines).
    rows = jnp.stack([
        mean / (std + _EPS) * ann,          # sharpe
        mean / (dstd + _EPS) * ann,         # sortino
        mdd,                                # max_drawdown
        eq_final - 1.0,                     # total_return
        jnp.power(final, 1.0 / years) - 1.0,  # cagr
        std * ann,                          # volatility
        hit,                                # hit_rate
        0.5 * turnover,                     # n_trades
        turnover,                           # turnover
    ], axis=0)                              # (9, lanes)
    return jnp.concatenate(
        [rows, jnp.zeros((_METRIC_ROWS - 9, pos.shape[-1]), jnp.float32)],
        axis=0)


def _sma_table(close_p, windows: tuple, W_pad: int):
    """Distinct-window SMA table, W-as-SUBLANE ``(N, W_pad, T_pad)``: one
    cumsum + W static shifts stacked on axis 1, keeping T_pad minor.

    Two things make this layout fast: the per-window rows are pure
    elementwise shift/sub/div expressions XLA fuses into one pass (a
    (T_pad, W)-indexed ``jnp.take`` lowered to a slow XLA gather that
    alone measured ~37% of the whole sweep — bench.py roofline_stages
    "prep" stage), and T_pad staying minor avoids the 128x tile-padding
    blow-up of a (N, T_pad, 1)-sliced stack on the lane axis. The kernel
    contracts the table's leading (window) axis directly, so no transpose
    is needed anywhere. Shared with bench.py's ``roofline_stages``
    scaffold so the measured and shipped preps cannot drift.
    """
    N, T_pad = close_p.shape
    cs = jnp.cumsum(close_p, axis=1)
    t_row = jnp.arange(T_pad)[None, :]                         # (1, T_pad)
    rows = []
    for w in windows:
        w = int(w)
        sma_w = (cs - _shift_t(cs, w, 0.0)) / jnp.float32(w)
        rows.append(jnp.where(t_row >= w - 1, sma_w, 0.0))
    rows += [jnp.zeros((N, T_pad), jnp.float32)] * (W_pad - len(windows))
    return jnp.stack(rows, axis=1)                       # (N, W_pad, T_pad)


def _sma_select_and_score(sma, r, od_ref, warm_ref, tr, out_ref, *,
                          cost: float, ppy: int, epilogue: str):
    """Shared SMA selection + metrics tail (both table substrates feed it).

    Per-lane window selection as MXU contractions over the table's
    LEADING window axis (the W-major layout lets the table build use
    static shifts instead of a gather — the gather version measured ~37%
    of the whole sweep; bench.py roofline_stages).
    ONE selection matmul on the DIFFERENCE one-hot (+1 at the fast row,
    -1 at the slow row): each lane's contraction has exactly two nonzero
    terms, so d == sma_fast - sma_slow and sign(d) is the crossover —
    half the MXU work of selecting f and s separately. The difference is
    now formed HOST-side (`_grid_setup` ships one ``(W_pad, lanes)``
    selector instead of two): exact 0/±1 integers either way, half the
    selector VMEM stream and one fewer per-cell pass. HIGHEST precision:
    the default bf16 pass truncates price-level SMAs enough to flip
    sign(d) near crossovers.
    """
    T_pad = sma.shape[1]
    d = jax.lax.dot_general(
        sma, od_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)   # (T_pad, lanes)

    lanes = od_ref.shape[1]   # wider-than-128 param blocks: fewer cells
                              # amortize per-cell overhead (bench.py
                              # roofline_stages measured +16% at 512)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]            # (1, lanes) max(fast, slow)
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    pos = jnp.where(valid, jnp.sign(d), 0.0)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


def _kernel(r_ref, sma_ref, od_ref, warm_ref, *refs,
            cost: float, ppy: int, T_real: int | None, epilogue: str):
    tr, out_ref = _unpack_tr(refs, T_real)
    r = r_ref[0]                     # (T_pad, 1) -> broadcasts over lanes
    sma = sma_ref[0]                 # (W_pad, T_pad) — W-major table
    _sma_select_and_score(sma, r, od_ref, warm_ref, tr, out_ref,
                          cost=cost, ppy=ppy, epilogue=epilogue)


def _kernel_inline(r_ref, cs_ref, od_ref, warm_ref, *refs,
                   cost: float, ppy: int, T_real: int | None,
                   windows: tuple, W_pad: int, epilogue: str):
    """The `_kernel` selection design with IN-KERNEL table construction.

    Instead of streaming an XLA-built ``(N, W_pad, T_pad)`` SMA table from
    HBM, this variant takes only the close cumsum ``(N, 1, T_pad)`` and
    rebuilds the W-major table into a persistent VMEM scratch once per
    ticker — at param-block ``j == 0``; the Pallas TPU grid is sequential
    (last axis innermost), so the scratch built there is still live for
    ``j = 1..n_blocks-1``. Row values use the exact op sequence of
    :func:`_sma_table` (sub, div by ``float32(w)``, warmup mask); the
    rotate's wrapped lanes are zeroed before the subtraction, reproducing
    ``_shift_t``'s zero fill. On CPU (interpret) the result is
    bit-identical to the HBM-table path (tested incl. multi-block). On
    TPU, Mosaic and XLA lower the f32 division differently, so some table
    entries differ by 1 ULP (measured: ~8% of entries for larger windows),
    which can flip knife-edge crossovers in ~0.01% of backtests — the same
    rounding class as the MXU selection matmul, and within every verify
    budget (bench --verify with this substrate: SMA 0/40000 entry flips,
    0 best-param flips). This removes the XLA table passes + the table
    HBM round-trip (measured ~4-5% median end-to-end, DESIGN.md).
    """
    *head, sma_scr = refs
    tr, out_ref = _unpack_tr(tuple(head), T_real)

    @pl.when(pl.program_id(1) == 0)
    def _build():
        _build_sma_scratch(cs_ref[0], sma_scr, windows, W_pad)

    r = r_ref[0]
    _sma_select_and_score(sma_scr[:], r, od_ref, warm_ref, tr,
                          out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


def _build_sma_scratch(cs, sma_scr, windows: tuple, W_pad: int):
    """Fill a ``(W_pad, T_pad)`` VMEM scratch with the W-major SMA table of
    the series whose cumsum row ``cs`` is ``(1, T_pad)`` — `_sma_table`'s
    exact op sequence (rotate + zero wrapped lanes, subtract, divide by
    ``float32(w)``, warmup mask). Shared by the SMA and OBV inline
    kernels; call under ``pl.when(j == 0)``."""
    T_pad = cs.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T_pad), 1)
    for k, w in enumerate(windows):
        w = int(w)
        if w < T_pad:
            shifted = jnp.where(lane >= w, _rot_lanes(cs, w), 0.0)
        else:
            shifted = jnp.zeros_like(cs)
        sma_w = (cs - shifted) / jnp.float32(w)
        sma_scr[k:k + 1, :] = jnp.where(lane >= w - 1, sma_w, 0.0)
    for k in range(len(windows), W_pad):
        # One-hot weights are zero on pad rows, but 0 * garbage VMEM
        # could still be NaN — zero them.
        sma_scr[k:k + 1, :] = jnp.zeros((1, T_pad), jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "table", "lanes_env", "epilogue"))
def _fused_call(close, onehot_d, warm, t_real, *, windows: tuple,
                T_pad: int, W_pad: int, P_real: int, T_real: int | None,
                cost: float, ppy: int, interpret: bool,
                table: str = "inline", lanes_env: int = 0,
                epilogue: str = _EPILOGUE_DEFAULT):
    """Table prep + pallas call in ONE jit: the prep is ~500 XLA ops and must
    not run eagerly (each eager op is a dispatch round-trip on the remote-
    proxy TPU backend — measured 13x slower end-to-end).

    ``table`` selects the SMA-table substrate: ``"inline"`` rebuilds it in
    VMEM scratch inside the kernel (`_kernel_inline` — no XLA table passes,
    no table HBM round-trip); ``"hbm"`` is the classic XLA-built
    ``(N, W_pad, T_pad)`` table streamed per ticker (`_kernel`), kept as
    the A/B twin the roofline stages are cut from. Bit-identical on CPU;
    on TPU see `_kernel_inline` for the 1-ULP division-lowering caveat.
    """
    N, T = close.shape
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    returns3 = _rets3(close_p)
    P_pad = onehot_d.shape[1]
    # sign kernel: no compose ladder
    lanes = _widest_lanes(P_pad, 512, T_pad, lanes_env)
    n_blocks = P_pad // lanes
    grid = (N, n_blocks)
    if table == "inline":
        cs = jnp.cumsum(close_p, axis=1)[:, None, :]       # (N, 1, T_pad)
        kernel = functools.partial(_kernel_inline, cost=cost, ppy=ppy,
                                   T_real=T_real, windows=windows,
                                   W_pad=W_pad, epilogue=epilogue)
        table_arg = cs
        table_spec = pl.BlockSpec((1, 1, T_pad), lambda i, j: (i, 0, 0),
                                  memory_space=pltpu.VMEM)
        scratch = [pltpu.VMEM((W_pad, T_pad), jnp.float32)]
    else:
        sma_table = _sma_table(close_p, windows, W_pad)
        kernel = functools.partial(_kernel, cost=cost, ppy=ppy,
                                   T_real=T_real, epilogue=epilogue)
        table_arg = sma_table
        table_spec = pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                                  memory_space=pltpu.VMEM)
        scratch = []
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            table_spec,
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(returns3, table_arg, onehot_d, warm,
      *_tr_args(t_real, T_real))
    # (N, n_blocks, 16, 128) -> nine (N, P_real) fields. The slice to P_real
    # stays inside the jit: eagerly slicing nine arrays after the call costs
    # nine dispatch round-trips on the remote-proxy backend.
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


def _check_carry_out_args(carry_out: bool, t_real) -> None:
    """Argument-only carry_out validation, hoisted to every wrapper's
    entry so an invalid call raises BEFORE the kernel sweep runs (the
    sweep is seconds of work at real shapes; the check is free)."""
    if carry_out and t_real is not None:
        raise ValueError(
            "carry_out=True supports uniform full-history panels only "
            "(a streaming checkpoint summarizes ONE panel state; ragged "
            "groups checkpoint per panel)")


def _carry_out_tail(metrics, strategy: str, fields: dict, grid: dict, *,
                    t_real, cost, ppy, epilogue):
    """The shared ``carry_out=True`` tail of every public sweep wrapper:
    return ``(metrics, carry)`` where the carry is the streaming
    checkpoint (``streaming.recurrent.StreamCarry``) of this sweep —
    the scan-form pass that makes every later ΔT-bar append O(ΔT)
    (``streaming.recurrent.append_step``). The carry is built by the
    generic-model scan form (the kernels' rounding twin on CPU, the
    documented knife-edge class on TPU); the kernel metrics are returned
    untouched alongside it. Argument validation lives in
    `_check_carry_out_args`, hoisted to the wrappers' entries."""
    del t_real   # validated (None) at wrapper entry
    from ..streaming import recurrent

    carry = recurrent.build_carry(
        strategy, fields, grid, cost=float(cost),
        periods_per_year=int(ppy), epilogue=epilogue)
    return metrics, carry


def fused_sma_sweep(close, fast, slow, *, t_real=None, cost: float = 0.0,
                    periods_per_year: int = 252,
                    interpret: bool | None = None,
                    table: str | None = None,
                    epilogue: str | None = None,
                    carry_out: bool = False) -> Metrics:
    """Fused SMA-crossover sweep: ``(N, T)`` closes x ``(P,)`` param lanes.

    ``fast``/``slow`` are the *flat* per-combo window arrays (use
    :func:`~..parallel.sweep.product_grid`), concrete (not traced) — the
    distinct-window table layout is computed host-side. Windows are bar
    counts and must be integral. Returns :class:`~.metrics.Metrics` with
    ``(N, P)`` fields matching the generic sweep path: bit-level on CPU; on
    TPU the MXU's 3xbf16 selection matmul can flip a *knife-edge* crossover
    (|fast_sma - slow_sma| ~ 1e-7 relative) — measured ~1 backtest in 8000
    differing by one round-trip on GBM data, all other entries tight.

    ``table`` picks the SMA-table substrate (default env ``DBX_SMA_TABLE``
    or ``"inline"``): ``"inline"`` rebuilds the W-major table in VMEM
    scratch inside the kernel once per ticker — no XLA table passes, no
    table HBM round-trip, measured ~1.04x median / up to ~1.15x the
    ``"hbm"`` headline on-chip — while ``"hbm"`` streams the XLA-built
    table (the roofline_stages scaffold's twin). Bit-identical on CPU
    (tested); on TPU the substrates can differ at ~0.01% of knife-edge
    crossovers (1-ULP division lowering, see `_kernel_inline`) — the
    fused-vs-generic verify budgets hold for both (bench --verify).
    ``epilogue`` picks the metrics-tail substrate (env ``DBX_EPILOGUE``,
    default ``"scan"`` — the single-pass carry scan; ``"ladder"`` keeps
    the O(T log T) shift-ladder fallback, see `_equity_scan`).
    ``carry_out=True`` additionally returns the streaming checkpoint of
    this sweep (see `_carry_out_tail`) as ``(metrics, carry)``.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    fast = np.asarray(fast)
    slow = np.asarray(slow)
    T = close.shape[1]
    P = fast.shape[0]

    windows, onehot_d, warm = _grid_setup(
        fast.astype(np.float32).tobytes(), slow.astype(np.float32).tobytes())
    table = _family_table("sma", table)
    m = _fused_call(close, onehot_d, warm,
                    _t_real_col(t_real, close),
                    windows=windows,
                    T_pad=_round_up(T, 8), W_pad=onehot_d.shape[0],
                    P_real=P, T_real=T if t_real is None else None,
                    cost=float(cost), ppy=int(periods_per_year),
                    interpret=bool(interpret), table=table,
                    lanes_env=resolve_lanes_cap(),
                    epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "sma_crossover", {"close": close},
                           {"fast": fast, "slow": slow}, t_real=t_real,
                           cost=cost, ppy=periods_per_year,
                           epilogue=epilogue)


def _prefix_compose3(pm, p0, pp):
    """Prefix-compose per-bar 3-state transition maps over the sublane axis.

    ``(pm, p0, pp)[t]`` give the next state when the previous state is
    -1/0/+1. Composition of such maps is associative, so the full position
    path evaluates as a log2(T_pad)-round doubling ladder — no serial scan
    (mirrors ``ops.signals.band_hysteresis_assoc``). Returns the composed
    maps; a start-state of flat means ``p0`` IS the position path.
    """
    T_pad = pm.shape[0]
    # Identity fill (-1/0/+1) pads the shifted reads.
    span = 1
    while span < T_pad:
        em = _shift_down(pm, span, -1.0)
        e0 = _shift_down(p0, span, 0.0)
        ep = _shift_down(pp, span, 1.0)
        pm, p0, pp = (
            jnp.where(em < 0, pm, jnp.where(em > 0, pp, p0)),
            jnp.where(e0 < 0, pm, jnp.where(e0 > 0, pp, p0)),
            jnp.where(ep < 0, pm, jnp.where(ep > 0, pp, p0)),
        )
        span *= 2
    return pm, p0, pp


def _compose3_path(pm, p0, pp, epilogue: str):
    """Position path of a 3-state machine from its per-bar transition maps,
    starting flat.

    ``"ladder"``: the full-T doubling ladder (`_prefix_compose3`), O(T log T).
    ``"scan"`` (default): ONE sequential pass over T-blocks — each block's
    maps compose locally (log2(B) rounds), the entry STATE carried from the
    previous block selects the component, and the block's last row is the
    next carry. Map composition and component selection are pure selects
    (no float arithmetic), so the two substrates are BIT-IDENTICAL on every
    backend; the scan does O(T log B) = O(T) work — the band machines'
    ~55%-of-tail compose cost (the 179-vs-76 ``vpu_ops_per_cell_bar``
    spread vs the sign kernels) drops to the sign kernels' class."""
    if epilogue == "ladder":
        _, p0, _ = _prefix_compose3(pm, p0, pp)
        return p0   # start state is flat: the 0-component is the path
    T_pad = pm.shape[0]
    state = None
    outs = []
    for s, e in _spans(T_pad, _scan_block(T_pad, epilogue)):
        m, z, p = _prefix_compose3(pm[s:e], p0[s:e], pp[s:e])
        pos = z if state is None else jnp.where(
            state < 0, m, jnp.where(state > 0, p, z))
        outs.append(pos)
        state = pos[e - s - 1:]
    return jnp.concatenate(outs, axis=0)


def _band_ladder(z, valid, k, z_exit, epilogue: str = _EPILOGUE_DEFAULT):
    """Band-hysteresis position path over ``(T_pad, 128)`` tiles, in-kernel.

    ``k``/``z_exit`` broadcast against the tile (scalars or (1, 128) lanes).
    """
    # Per-bar transition maps (next state when previous state is -1/0/+1).
    entered = jnp.where(z < -k, 1.0, jnp.where(z > k, -1.0, 0.0))
    pm = jnp.where(valid & (z > z_exit), -1.0, 0.0)
    p0 = jnp.where(valid, entered, 0.0)
    pp = jnp.where(valid & (z < -z_exit), 1.0, 0.0)
    return _compose3_path(pm, p0, pp, epilogue)


def _band_cell_core(z_wt, r_ref, ow_ref, k_ref, warm_ref, refs, T_real):
    """Shared head of every band-family cell (Bollinger hysteresis, band
    touch; RSI and VWAP reuse those kernels): ragged/uniform unpack, the
    z-selection matmul, warmup mask and band lanes.

    ``z_wt`` is the ``(W_pad, T_pad)`` z-table VALUE — read from an HBM-
    streamed input block or from the in-kernel VMEM scratch build; T on
    lanes, so HBM tiling pads W to a sublane multiple (8) instead of a
    lane multiple (128); at the baseline grid's ~20 distinct windows the
    old (T, W)-minor layout inflated every table and prep intermediate
    6.4x (same fix as the pairs kernel). Returns
    ``(tr, out_ref, r, z, t_idx, valid, k)``.
    """
    tr, out_ref = _unpack_tr(refs, T_real)
    T_pad = r_ref.shape[1]
    r = r_ref[0]                     # (T_pad, 1)
    dn = (((0,), (0,)), ((), ()))
    z = jax.lax.dot_general(z_wt, ow_ref[:], dn,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)  # (T_pad,128)

    lanes = ow_ref.shape[1]
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    k = k_ref[0, :][None, :]                         # (1, lanes) entry band
    return tr, out_ref, r, z, t_idx, valid, k


def _band_cell_prologue(r_ref, z_ref, ow_ref, k_ref, warm_ref, refs, T_real):
    """`_band_cell_core` over an HBM-streamed ``(1, W_pad, T_pad)`` block."""
    return _band_cell_core(z_ref[0], r_ref, ow_ref, k_ref, warm_ref, refs,
                           T_real)


def _band_cell_finish(machine: str, z, valid, k, z_exit, r, t_idx, tr,
                      out_ref, *, cost: float, ppy: int, epilogue: str):
    """Tail of both Bollinger-family cells — one body for both table
    substrates so the position semantics cannot drift between them.

    ``"hysteresis"``: the 3-state band machine (enter outside ±k, exit
    through ±z_exit). ``"touch"``: memoryless — exposure is which band
    you are currently outside of (``models.bollinger.bollinger_touch``),
    so the compose ladder drops out entirely."""
    if machine == "touch":
        pos = jnp.where(z < -k, 1.0, jnp.where(z > k, -1.0, 0.0))
        pos = jnp.where(valid, pos, 0.0)
    else:
        pos = _band_ladder(z, valid, k, z_exit, epilogue)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


def _boll_kernel(r_ref, z_ref, ow_ref, k_ref, warm_ref, *refs,
                 cost: float, ppy: int, z_exit: float,
                 T_real: int | None, epilogue: str = _EPILOGUE_DEFAULT):
    """Bollinger mean-reversion cell: z-selection matmul + hysteresis
    machine (blocked compose scan by default, see `_compose3_path`)."""
    tr, out_ref, r, z, t_idx, valid, k = _band_cell_prologue(
        r_ref, z_ref, ow_ref, k_ref, warm_ref, refs, T_real)
    _band_cell_finish("hysteresis", z, valid, k, z_exit, r, t_idx, tr,
                      out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


def _touch_kernel(r_ref, z_ref, ow_ref, k_ref, warm_ref, *refs,
                  cost: float, ppy: int, z_exit: float,
                  T_real: int | None, epilogue: str = _EPILOGUE_DEFAULT):
    """Band-touch cell: the memoryless Bollinger variant (see
    :func:`_band_cell_finish`). ``z_exit`` is unused (the machine has no
    exit memory); the parameter stays so the kernel is plug-compatible
    with ``_boll_kernel`` in :func:`_fused_boll_call`."""
    tr, out_ref, r, z, t_idx, valid, k = _band_cell_prologue(
        r_ref, z_ref, ow_ref, k_ref, warm_ref, refs, T_real)
    _band_cell_finish("touch", z, valid, k, z_exit, r, t_idx, tr,
                      out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


def _build_boll_z_scratch(c, cs, csx, csx2, z_scr, windows: tuple,
                          W_pad: int):
    """Fill a ``(W_pad, T_pad)`` VMEM scratch with the W-major Bollinger
    z-table of the series whose close row / close cumsum / centered cumsum
    / centered-square cumsum rows are ``(1, T_pad)`` each — the exact op
    sequence of `_fused_boll_call`'s XLA prep (cumsum-difference windowed
    sums, rolling.py's series-centered cancellation guard, eps=1e-12,
    warmup zero-fill), with `_shift_t`'s zero fill reproduced as
    rotate + zero the wrapped lanes. Call under ``pl.when(j == 0)``."""
    T_pad = cs.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T_pad), 1)
    for i, w in enumerate(windows):
        w = int(w)

        def wsum(row):
            if w < T_pad:
                shifted = jnp.where(lane >= w, _rot_lanes(row, w), 0.0)
            else:
                shifted = jnp.zeros_like(row)
            return row - shifted

        w_f = jnp.float32(w)
        m = wsum(cs) / w_f
        s1 = wsum(csx)
        s2 = wsum(csx2)
        var = jnp.maximum((s2 - s1 * s1 / w_f) / w_f, 0.0)
        z_w = (c - m) / (jnp.sqrt(var) + 1e-12)
        z_scr[i:i + 1, :] = jnp.where(lane >= w - 1, z_w, 0.0)
    for i in range(len(windows), W_pad):
        # One-hot weights are zero on pad rows, but 0 * garbage VMEM
        # could still be NaN — zero them (same discipline as
        # `_build_sma_scratch`).
        z_scr[i:i + 1, :] = jnp.zeros((1, T_pad), jnp.float32)


def _band_kernel_inline(r_ref, c_ref, cs_ref, csx_ref, csx2_ref, ow_ref,
                        k_ref, warm_ref, *refs, cost: float, ppy: int,
                        z_exit: float, T_real: int | None, machine: str,
                        windows: tuple, W_pad: int,
                        epilogue: str = _EPILOGUE_DEFAULT):
    """Both Bollinger-family cells with IN-KERNEL z-table construction.

    Takes the close row plus three cumsum rows ``(N, 1, T_pad)`` instead
    of the XLA-built ``(N, W_pad, T_pad)`` z-table and rebuilds the
    W-major table into persistent VMEM scratch once per ticker at
    param-block ``j == 0`` (same scratch-persistence contract as
    `_kernel_inline`). This deletes the largest XLA prep in the file —
    three windowed sums + var/sqrt over table-shaped intermediates — and
    the z-table HBM round-trip (~61 MB at headline shapes; the prep
    measured ~17% of bollinger's and ~34% of touch's end-to-end wall).
    Bit-identical on CPU interpret mode (tested); on TPU Mosaic's f32
    div/sqrt lowering differs from XLA's by ~1 ULP on some entries — the
    knife-edge flip class every verify budget already covers."""
    *head, z_scr = refs

    @pl.when(pl.program_id(1) == 0)
    def _build():
        _build_boll_z_scratch(c_ref[0], cs_ref[0], csx_ref[0], csx2_ref[0],
                              z_scr, windows, W_pad)

    tr, out_ref, r, z, t_idx, valid, k = _band_cell_core(
        z_scr[:], r_ref, ow_ref, k_ref, warm_ref, tuple(head), T_real)
    _band_cell_finish(machine, z, valid, k, z_exit, r, t_idx, tr,
                      out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


_BAND_KERNELS = {"hysteresis": _boll_kernel, "touch": _touch_kernel}


def _pad_w(tbl, W_pad: int):
    """Zero-pad an ``(N, W, T_pad)`` table's window axis up to ``W_pad``."""
    N, W, T_pad = tbl.shape
    if W_pad == W:
        return tbl
    return jnp.concatenate(
        [tbl, jnp.zeros((N, W_pad - W, T_pad), jnp.float32)], axis=1)


def _cumsum_window_tools(windows: tuple, T_pad: int):
    """Scaffolding for per-distinct-window cumsum-difference rolling sums.

    Returns ``(w_col, w_f, t_row, windowed_sum, windowed_sum3)`` where the
    two closures map ``(N, T_pad)`` / ``(N, W, T_pad)`` inputs to windowed
    trailing sums, replicating ``rolling.rolling_sum``'s exact float op
    order (inclusive prefix sum minus the clipped-gather shifted read).
    Tables built with these are (N, W, T_pad) — T on the minor axis — so
    HBM tiling pads W to a sublane multiple (8), not a lane multiple (128).
    """
    w_col = jnp.asarray(np.asarray(windows, np.int32))[:, None]  # (W,1)
    w_f = w_col.astype(jnp.float32)[None]                        # (1,W,1)
    t_row = jnp.arange(T_pad)[None, :]                           # (1,T_pad)

    def windowed_sum(series):                                    # (N,T_pad) ->
        # Per-window shifted reads as STATIC slice+concat (plain copies
        # XLA fuses), NOT a (W, T_pad)-indexed gather: the gather version
        # of the SMA table measured ~37% of that whole sweep (bench.py
        # roofline_stages), and windowed_sum3 below learned the same
        # lesson earlier. Bit-identical: window rows are compile-time
        # constants, zero-filled for t < w exactly like the old
        # clipped-gather + in-window mask.
        cs = jnp.cumsum(series, axis=1)                          # (N,T_pad)
        N = series.shape[0]
        zero = jnp.zeros((N, 1), jnp.float32)
        shifted = jnp.stack(
            [jnp.concatenate(
                [jnp.broadcast_to(zero, (N, min(int(w), T_pad))),
                 cs[:, :T_pad - min(int(w), T_pad)]], axis=1)
             for w in windows], axis=1)                          # (N,W,T_pad)
        return cs[:, None, :] - shifted

    def windowed_sum3(series):                                   # (N,W,T_pad)
        # Per-row shifted reads as STATIC slice+concat, not take_along_axis:
        # the 3-D gather measured ~185 ms alone at the 500x20x1280 baseline
        # (the cumsum itself is ~12 ms); static shifts are plain copies and
        # bit-identical (window rows are compile-time constants here).
        cs = jnp.cumsum(series, axis=2)
        N = series.shape[0]
        zero = jnp.zeros((N, 1), jnp.float32)
        # min(w, T_pad): a window covering the whole padded axis has no
        # shifted read at all (the old clipped-gather + in-window mask
        # yielded an all-zero row there — same result, and the warmup mask
        # downstream keeps such degenerate lanes flat anyway).
        shifted = jnp.stack(
            [jnp.concatenate(
                [jnp.broadcast_to(zero, (N, min(w, T_pad))),
                 cs[:, i, :T_pad - min(w, T_pad)]], axis=1)
             for i, w in enumerate(windows)], axis=1)
        return cs - shifted

    return w_col, w_f, t_row, windowed_sum, windowed_sum3


def _band_machine_pallas(kernel, close_p, z_table, onehot_w, k_lanes, warm,
                         t_real, *, T_pad: int, W_pad: int, P_real: int,
                         T_real: int | None, interpret: bool,
                         lanes_cap: int = 256, aux_rows=(),
                         scratch_shapes=(), lanes_env: int = 0):
    """Shared launch for every band-machine strategy (Bollinger, RSI, VWAP):
    returns column + ``(N, W_pad, T_pad)`` z-table + one-hot/band/warmup
    lanes into ``_boll_kernel``-shaped cells, :class:`Metrics` out.

    ``lanes_cap`` defaults to 256 — the hysteresis cell's 3-state compose
    ladder keeps ~6 (T_pad, lanes) arrays live, so 512 lanes would press
    the VMEM budget. The ladder-free touch cell overrides to 512 (sign-
    kernel class).

    ``z_table=None`` selects the in-kernel substrate: ``aux_rows`` (each
    ``(N, T_pad)``, delivered as ``(1, 1, T_pad)`` lane-major blocks) and
    ``scratch_shapes`` carry the VMEM-scratch z-table build instead
    (`_band_kernel_inline`)."""
    N = close_p.shape[0]
    P_pad = k_lanes.shape[1]
    lanes = _widest_lanes(P_pad, lanes_cap, T_pad, lanes_env)
    n_blocks = P_pad // lanes
    table_specs = [] if z_table is None else [
        pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM)]
    table_args = [] if z_table is None else [z_table]
    aux_specs = [
        pl.BlockSpec((1, 1, T_pad), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM)
        for _ in aux_rows
    ]
    out = pl.pallas_call(
        kernel,
        grid=(N, n_blocks),
        in_specs=[
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ] + table_specs + aux_specs + [
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        scratch_shapes=list(scratch_shapes),
        interpret=interpret,
    )(_rets3(close_p), *table_args,
      *(row[:, None, :] for row in aux_rows), onehot_w, k_lanes, warm,
      *_tr_args(t_real, T_real))
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "z_exit", "machine", "interpret", "table",
                     "lanes_env", "epilogue"))
def _fused_boll_call(close, onehot_w, k_lanes, warm, t_real, *, windows: tuple,
                     T_pad: int, W_pad: int, P_real: int, T_real: int | None,
                     cost: float, ppy: int, z_exit: float, interpret: bool,
                     machine: str = "hysteresis", table: str = "inline",
                     lanes_env: int = 0, epilogue: str = _EPILOGUE_DEFAULT):
    """Z-score table prep + pallas call in one jit (same dispatch-economy
    rationale as ``_fused_call``).

    The table replicates ``rolling.rolling_zscore``'s exact float op order so
    CPU interpret-mode results are bit-identical to the generic path:
    numerator from the *uncentered* rolling mean, std from series-centered
    second moments (rolling.py's cancellation guard), eps=1e-12.

    ``table="inline"`` (default) ships only the close row + three cumsum
    rows to the kernel and rebuilds the z-table in VMEM scratch
    (`_band_kernel_inline`) — the three windowed sums + var/sqrt XLA prep
    and the z-table HBM round-trip measured ~17% (hysteresis) / ~34%
    (touch) of end-to-end wall at headline shapes. ``"hbm"`` keeps the
    XLA-built table as the A/B twin.
    """
    N, T = close.shape
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    # The memoryless touch cell has no compose ladder: sign-kernel VMEM
    # class, so it takes the sign kernels' 512-lane blocks (measured +5%
    # in the 3x interleaved on-chip A/B).
    lanes_cap = 512 if machine == "touch" else 256
    # Center with the mean over the REAL bars only (the generic path sees the
    # unpadded series); the pad region's xc values never reach a real output.
    xc = close_p - jnp.mean(close_p[:, :T], axis=1, keepdims=True)
    if table == "inline":
        kernel = functools.partial(_band_kernel_inline, cost=cost, ppy=ppy,
                                   z_exit=z_exit, T_real=T_real,
                                   machine=machine, windows=windows,
                                   W_pad=W_pad, epilogue=epilogue)
        return _band_machine_pallas(
            kernel, close_p, None, onehot_w, k_lanes, warm, t_real,
            T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
            interpret=interpret, lanes_cap=lanes_cap,
            aux_rows=[close_p, jnp.cumsum(close_p, axis=1),
                      jnp.cumsum(xc, axis=1), jnp.cumsum(xc * xc, axis=1)],
            scratch_shapes=[pltpu.VMEM((W_pad, T_pad), jnp.float32)],
            lanes_env=lanes_env)

    w_col, w_f, t_row, windowed_sum, _ = _cumsum_window_tools(windows, T_pad)
    m = windowed_sum(close_p) / w_f                              # rolling mean
    s1 = windowed_sum(xc)
    s2 = windowed_sum(xc * xc)
    var = jnp.maximum((s2 - s1 * s1 / w_f) / w_f, 0.0)
    z_table = (close_p[:, None, :] - m) / (jnp.sqrt(var) + 1e-12)
    z_table = _pad_w(jnp.where((t_row >= w_col - 1)[None], z_table, 0.0),
                     W_pad)

    kernel = functools.partial(_BAND_KERNELS[machine], cost=cost, ppy=ppy,
                               z_exit=z_exit, T_real=T_real,
                               epilogue=epilogue)
    return _band_machine_pallas(
        kernel, close_p, z_table, onehot_w, k_lanes, warm, t_real,
        T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
        interpret=interpret, lanes_cap=lanes_cap, lanes_env=lanes_env)


def _bollinger_family_sweep(close, window, k, *, machine: str, z_exit: float,
                            t_real, cost: float, periods_per_year: int,
                            interpret: bool | None,
                            table: str | None = None,
                            epilogue: str | None = None,
                            carry_out: bool = False) -> Metrics:
    """Shared prep for both Bollinger-family wrappers (one z-table/grid
    pipeline, the ``machine`` picks the cell; ``table`` picks the z-table
    substrate — env ``DBX_BOLL_TABLE`` or ``"inline"``)."""
    _check_carry_out_args(carry_out, t_real)
    if carry_out and machine == "hysteresis" and float(z_exit) != 0.0:
        raise ValueError(
            "carry_out=True requires z_exit=0 for the bollinger machine "
            "(the streaming family follows models.bollinger, which exits "
            "at the rolling mean)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    window = np.asarray(window)
    k = np.asarray(k, np.float32)
    T = close.shape[1]

    windows, onehot_w, k_lanes, warm = _boll_grid_setup(
        window.astype(np.float32).tobytes(), k.tobytes())
    # T_pad is a lane multiple (128): T sits on the table's minor axis AND
    # on the working tiles' sublane axis.
    m = _fused_boll_call(close, onehot_w, k_lanes, warm,
                         _t_real_col(t_real, close),
                         windows=windows,
                         T_pad=_round_up(T, 128), W_pad=onehot_w.shape[0],
                         P_real=window.shape[0],
                         T_real=T if t_real is None else None,
                         cost=float(cost), ppy=int(periods_per_year),
                         z_exit=float(z_exit), machine=machine,
                         interpret=bool(interpret),
                         table=_family_table("boll", table),
                         lanes_env=resolve_lanes_cap(),
                         epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(
        m, "bollinger" if machine == "hysteresis" else "bollinger_touch",
        {"close": close}, {"window": window, "k": k}, t_real=t_real,
        cost=cost, ppy=periods_per_year, epilogue=epilogue)


def fused_bollinger_touch_sweep(close, window, k, *, t_real=None,
                                cost: float = 0.0,
                                periods_per_year: int = 252,
                                interpret: bool | None = None,
                                table: str | None = None,
                                epilogue: str | None = None,
                                carry_out: bool = False) -> Metrics:
    """Fused band-touch sweep: the path-free Bollinger variant.

    Same z-table and grid layout as :func:`fused_bollinger_sweep`, but the
    position is memoryless (long/short while outside the ±k band, flat
    inside — ``models.bollinger.bollinger_touch``), so the cell skips the
    hysteresis ladder. Matches ``run_sweep(..., "bollinger_touch")``:
    bit-level on CPU interpret mode; the usual MXU knife-edge caveat on
    TPU.
    """
    return _bollinger_family_sweep(
        close, window, k, machine="touch", z_exit=0.0, t_real=t_real,
        cost=cost, periods_per_year=periods_per_year, interpret=interpret,
        table=table, epilogue=epilogue, carry_out=carry_out)


def fused_bollinger_sweep(close, window, k, *, t_real=None,
                          z_exit: float = 0.0,
                          cost: float = 0.0, periods_per_year: int = 252,
                          interpret: bool | None = None,
                          table: str | None = None,
                          epilogue: str | None = None,
                          carry_out: bool = False) -> Metrics:
    """Fused Bollinger mean-reversion sweep: ``(N, T)`` closes x ``(P,)`` lanes.

    ``window``/``k`` are flat per-combo arrays (:func:`product_grid` order);
    windows must be integral bar counts. Matches the generic
    ``run_sweep(..., "bollinger")`` path (``models.bollinger`` +
    ``signals.band_hysteresis_assoc``): bit-level on CPU interpret mode; on
    TPU the MXU z-selection matmul shares the SMA kernel's knife-edge caveat
    for |z - k| ~ 1e-7 relative. BASELINE.json configs[2] is this workload.
    """
    return _bollinger_family_sweep(
        close, window, k, machine="hysteresis", z_exit=z_exit,
        t_real=t_real, cost=cost, periods_per_year=periods_per_year,
        interpret=interpret, table=table, epilogue=epilogue,
        carry_out=carry_out)




def _distinct_windows(vals: np.ndarray, what: str) -> np.ndarray:
    """Validate integral bar counts and return the sorted distinct windows."""
    if not np.allclose(vals, np.round(vals)):
        raise ValueError(
            f"fused sweep {what} are bar counts and must be integral; got "
            f"non-integer values "
            f"(e.g. {vals[~np.isclose(vals, np.round(vals))][0]})")
    return np.unique(np.round(vals)).astype(np.float32)


def _window_onehot(windows: np.ndarray, vals: np.ndarray, W_pad: int,
                   P_pad: int) -> np.ndarray:
    """(W_pad, P_pad) selector, one 1.0 per real lane.

    Search with the same rounding used to build ``windows``, or a value
    like 200.001 (passes the integrality tolerance) lands one row off.
    """
    oh = np.zeros((W_pad, P_pad), np.float32)
    idx = np.searchsorted(windows, np.round(vals).astype(np.float32))
    oh[idx, np.arange(vals.shape[0])] = 1.0
    return oh


@functools.lru_cache(maxsize=4)
def _boll_grid_setup(window_bytes: bytes, k_bytes: bytes):
    """Distinct windows + device-resident one-hot/k/warmup lanes (cached, same
    rationale as :func:`_grid_setup`)."""
    window = np.frombuffer(window_bytes, np.float32)
    k = np.frombuffer(k_bytes, np.float32)
    P = window.shape[0]
    windows = _distinct_windows(window, "windows")
    # One-hot contracts over W as the *sublane* dim of both operands (the
    # table is (W, T)-major), so W pads to 8, not 128.
    W_pad = _round_up(max(windows.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    oh = _window_onehot(windows, window, W_pad, P_pad)

    k_lanes = np.full((1, P_pad), np.float32(np.inf))
    k_lanes[0, :P] = k            # padded lanes never enter (k = +inf)
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = window
    return (tuple(int(w) for w in windows), _const(oh),
            _const(k_lanes), _const(warm))


def _pairs_kernel(zh_ref, ow_ref, k_ref, zx_ref,
                  warm_ref, *refs, cost: float, ppy: int,
                  T_real: int | None, epilogue: str = _EPILOGUE_DEFAULT):
    """Pairs-trade cell: one stacked selection matmul + hysteresis + PnL.

    The per-pair z-score and *hedged-return* tables arrive stacked along
    the lane (T) axis as one ``(W_pad, 2*T_pad)`` block, so ONE MXU
    contraction selects both per lane — the skinny (K = W_pad) selection
    matmuls are pass-overhead-bound, and prep already knows the spread
    return ``(r_y - prev_beta * r_x) / max(1 + |prev_beta|, 1)``
    (gross-exposure normalized, mirroring ``models.pairs.pair_backtest``;
    the beta shift is baked in). The shared band ladder turns z into the
    position path; ``net = prev_pos * hr - cost * |Δpos|`` shares only
    ``_metrics_pack`` with the single-asset tail.
    """
    tr, out_ref = _unpack_tr(refs, T_real)
    T_pad = zh_ref.shape[2] // 2
    # The table is (W_pad, 2*T_pad) — T on lanes, so the HBM layout pads W
    # up to a sublane multiple (8) instead of a lane multiple (128); the
    # 12.8x HBM blow-up of a W-minor table layout dominated the first cut
    # of this kernel (measured: 601 of 716 ms/sweep in prep). The selection
    # contracts dim 0 of both operands (tbl^T @ onehot on the MXU).
    dn = (((0,), (0,)), ((), ()))
    zh = jax.lax.dot_general(zh_ref[0], ow_ref[:], dn,
                             preferred_element_type=jnp.float32,
                             precision=jax.lax.Precision.HIGHEST)
    z = zh[:T_pad]                                     # (T_pad, lanes)
    hr = zh[T_pad:]                                    # hedged spread return

    lanes = ow_ref.shape[1]          # widest legal param block (launcher)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]                     # (1, lanes) = 2*lb - 1
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    k = k_ref[0, :][None, :]                           # per-lane z_entry
    zx = zx_ref[0, :][None, :]                         # per-lane z_exit

    pos = _band_ladder(z, valid, k, zx, epilogue)

    row_ok = t_idx < tr
    pos_last = _row_at(pos, tr, t_idx, keepdims=True)
    pos = jnp.where(row_ok, pos, pos_last)
    prev = _shift_down(pos, 1, 0.0)
    net = prev * hr - cost * jnp.abs(pos - prev)
    out_ref[0, 0] = _metrics_pack(pos, prev, net, row_ok, t_idx, tr,
                                  ppy=ppy, epilogue=epilogue)


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_pairs_call(y_close, x_close, onehot_w, k_lanes, zx_lanes, warm,
                      t_real, *,
                      windows: tuple, T_pad: int, W_pad: int, P_real: int,
                      T_real: int | None,
                      cost: float, ppy: int, interpret: bool,
                      epilogue: str = _EPILOGUE_DEFAULT):
    """Beta/z table prep + pallas call in one jit.

    The tables follow ``rolling.rolling_ols`` / ``rolling.rolling_zscore``'s
    formulas (series-centered moments, eps=1e-12, warmup fill 0 so the warmup
    spread is exactly ``y`` — ``models.pairs.pair_signals`` semantics). Both
    the OLS moment sums and the z-score's per-(pair, window) sums ride the
    same cumsum-differencing closures as the generic path
    (:func:`_cumsum_window_tools`), so the whole signal prep rounds like the
    reference algebra (see :func:`fused_pairs_sweep`).
    """
    N, T = y_close.shape
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    y_p, x_p = _pad_last(y_close, T_pad), _pad_last(x_close, T_pad)

    # Tables are built (N, W, T_pad) — T on the minor axis — so HBM tiling
    # pads W to a sublane multiple (8) rather than a lane multiple (128).
    # BOTH the per-pair OLS moments and the per-(pair, window) z-score sums
    # ride cumsum-difference closures that replicate
    # ``rolling.rolling_sum``'s exact float op order (inclusive prefix sum
    # minus a static shifted read). Selection-stability is why (round 4):
    # the previous block-banded MXU tree sums evaluated the z windowed sums
    # in a different summation order than the generic path's cumsum
    # difference, and that rounding gap was the fleet's worst entry-flip
    # rate (0.77% of cells, the only unstable best-param argmax in
    # VERIFY_r03). Matching the op order collapses the disagreement to the
    # same class as the other kernels; the 3-D minor-axis cumsum with
    # static per-row shifts costs about the same as the two band einsums it
    # replaces (A/B'd as full entry-point timings on the chip, see
    # DESIGN.md).
    w_col, w_f, t_row, windowed_sum, windowed_sum3 = _cumsum_window_tools(
        windows, T_pad)

    # Rolling OLS of y on x per distinct lookback (closed form from windowed
    # moments; centering with the real-bar means kills f32 cancellation —
    # same guard as rolling.rolling_ols).
    mx = jnp.mean(x_p[:, :T], axis=1, keepdims=True)             # (N,1)
    my = jnp.mean(y_p[:, :T], axis=1, keepdims=True)
    xc, yc = x_p - mx, y_p - my
    sx = windowed_sum(xc)
    sy = windowed_sum(yc)
    sxx = windowed_sum(xc * xc)
    sxy = windowed_sum(xc * yc)
    cov = sxy - sx * sy / w_f
    var = jnp.maximum(sxx - sx * sx / w_f, 0.0)
    beta = cov / (var + 1e-12)
    mx3, my3 = mx[:, :, None], my[:, :, None]                    # (N,1,1)
    alpha = (sy / w_f + my3) - beta * (sx / w_f + mx3)
    ok_w = (t_row >= w_col - 1)[None]                            # OLS warmup
    beta_tbl = jnp.where(ok_w, beta, 0.0)
    # Warmup spread is y - (0 + 0*x) = y (rolling_ols fill=0.0); those bars
    # feed the z-score's *series mean* and early windowed sums, so they must
    # hold exactly y for parity with the generic path.
    y3, x3 = y_p[:, None, :], x_p[:, None, :]
    spread = jnp.where(ok_w, y3 - (alpha + beta * x3), y3)

    # Rolling z-score of the spread over the same lookback.
    sp_mean = jnp.mean(spread[..., :T], axis=-1, keepdims=True)
    sc = spread - sp_mean
    s1 = windowed_sum3(sc)
    s2 = windowed_sum3(sc * sc)
    varz = jnp.maximum((s2 - s1 * s1 / w_f) / w_f, 0.0)
    mz = windowed_sum3(spread) / w_f
    z = (spread - mz) / (jnp.sqrt(varz) + 1e-12)
    # Valid only after OLS warmup + z warmup: t >= 2*lb - 2. Zeroing the rest
    # also keeps NaN/Inf out of the selection matmul.
    z_tbl = jnp.where((t_row >= 2 * w_col - 2)[None], z, 0.0)

    # Hedged spread return per (pair, window), beta shift baked in: the
    # kernel's net is just prev_pos * hr - costs, and ONE stacked selection
    # matmul picks (z, hr) per lane instead of separate z/beta contractions
    # (the K = W_pad matmul is pass-overhead-bound, so halving the passes
    # matters more than the FLOPs). Same float op order as the old
    # in-kernel form — prev_beta, gross, and the division are untouched.
    ry = _rets3(y_p)[:, :, 0][:, None, :]                        # (N,1,T_pad)
    rx = _rets3(x_p)[:, :, 0][:, None, :]
    beta_prev = jnp.concatenate(
        [jnp.zeros((N, beta_tbl.shape[1], 1), jnp.float32),
         beta_tbl[:, :, :-1]], axis=2)
    gross = 1.0 + jnp.abs(beta_prev)
    hr_tbl = (ry - beta_prev * rx) / jnp.maximum(gross, 1.0)

    if W_pad > len(windows):
        zpad = jnp.zeros((N, W_pad - len(windows), T_pad), jnp.float32)
        z_tbl = jnp.concatenate([z_tbl, zpad], axis=1)
        hr_tbl = jnp.concatenate([hr_tbl, zpad], axis=1)
    zh_tbl = jnp.concatenate([z_tbl, hr_tbl], axis=2)   # (N, W_pad, 2*T_pad)

    P_pad = k_lanes.shape[1]
    # 256-lane cap: the band ladder + two (T_pad, lanes) selection halves
    # keep the same VMEM budget class as the band machines.
    lanes = _widest_lanes(P_pad, 256)
    n_blocks = P_pad // lanes
    kernel = functools.partial(_pairs_kernel, cost=cost, ppy=ppy,
                               T_real=T_real, epilogue=epilogue)
    out = pl.pallas_call(
        kernel,
        grid=(N, n_blocks),
        in_specs=[
            pl.BlockSpec((1, W_pad, 2 * T_pad), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        interpret=interpret,
    )(zh_tbl, onehot_w, k_lanes, zx_lanes,
      warm, *_tr_args(t_real, T_real))
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


def fused_pairs_sweep(y_close, x_close, lookback, z_entry, *, t_real=None,
                      z_exit=0.0,
                      cost: float = 0.0, periods_per_year: int = 252,
                      interpret: bool | None = None,
                      epilogue: str | None = None,
                      carry_out: bool = False) -> Metrics:
    """Fused rolling-OLS pairs sweep: ``(N, T)`` pair legs x ``(P,)`` lanes.

    ``lookback``/``z_entry`` are flat per-combo arrays (:func:`product_grid`
    order); ``z_exit`` may be a scalar or a per-combo array. Lookbacks are bar
    counts and must be integral. Matches :func:`~..models.pairs.run_pairs_sweep`
    (BASELINE.json configs[3]) to f32 tolerance: every windowed sum in the
    prep — the OLS moments AND the spread z-score's — is cumsum-differenced
    in ``rolling.rolling_sum``'s exact float op order, so beta/alpha/z all
    round like the generic path and only MXU-selection knife edges remain.
    (Round 4: the z sums were previously block-banded MXU tree sums, whose
    different summation order made pairs the fleet's worst entry-flip rate
    — 0.77% of cells and the only unstable best-param argmax; matching the
    op order measured 7/20000 cells = 0.035% flips, best-param flips 0, and
    8.33 vs 7.96 M/s. ``bench.py --verify`` re-quantifies and BUDGETS both
    every round.)
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    y_close = jnp.asarray(y_close, jnp.float32)
    x_close = jnp.asarray(x_close, jnp.float32)
    lookback = np.asarray(lookback, np.float32)
    z_entry = np.asarray(z_entry, np.float32)
    z_exit_arr = np.ascontiguousarray(
        np.broadcast_to(np.asarray(z_exit, np.float32), lookback.shape))
    T = y_close.shape[1]
    P = lookback.shape[0]

    windows, onehot_w, k_lanes, zx_lanes, warm = _pairs_grid_setup(
        lookback.tobytes(), z_entry.tobytes(), z_exit_arr.tobytes())
    # T_pad is a lane multiple (128): T sits on the tables' minor axis AND on
    # the working tiles' sublane axis, so 128 satisfies both constraints.
    m = _fused_pairs_call(y_close, x_close, onehot_w, k_lanes, zx_lanes,
                          warm, _t_real_col(t_real, y_close),
                          windows=windows,
                          T_pad=_round_up(T, 128), W_pad=onehot_w.shape[0],
                          P_real=P, T_real=T if t_real is None else None,
                          cost=float(cost),
                          ppy=int(periods_per_year),
                          interpret=bool(interpret),
                          epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(
        m, "pairs", {"close": y_close, "close2": x_close},
        {"lookback": lookback, "z_entry": z_entry, "z_exit": z_exit_arr},
        t_real=t_real, cost=cost, ppy=periods_per_year, epilogue=epilogue)


@functools.lru_cache(maxsize=4)
def _pairs_grid_setup(lb_bytes: bytes, ze_bytes: bytes, zx_bytes: bytes):
    """Distinct lookbacks + device-resident one-hot/band/warmup lanes
    (cached, same rationale as :func:`_grid_setup`)."""
    lookback = np.frombuffer(lb_bytes, np.float32)
    z_entry = np.frombuffer(ze_bytes, np.float32)
    z_exit = np.frombuffer(zx_bytes, np.float32)
    P = lookback.shape[0]
    windows = _distinct_windows(lookback, "lookbacks")
    # The one-hot contracts over W as the *sublane* dim of both operands
    # (tables are (W, T)-major), so W pads to 8, not 128.
    W_pad = _round_up(max(windows.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    oh = _window_onehot(windows, lookback, W_pad, P_pad)

    k_lanes = np.full((1, P_pad), np.float32(np.inf))
    k_lanes[0, :P] = z_entry      # padded lanes never enter (z_entry = +inf)
    zx_lanes = np.zeros((1, P_pad), np.float32)
    zx_lanes[0, :P] = z_exit
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = 2.0 * lookback - 1.0   # OLS warmup + z-score warmup
    return (tuple(int(w) for w in windows), _const(oh),
            _const(k_lanes), _const(zx_lanes), _const(warm))


@functools.lru_cache(maxsize=4)
def _grid_setup(fast_bytes: bytes, slow_bytes: bytes):
    """Distinct windows + device-resident one-hot/warmup arrays per grid.

    Cached: rebuilding these in numpy per call forces a fresh host->device
    transfer of ~2 MB every sweep — a measurable cost on the remote-proxy
    backend for a sub-100ms kernel. The cache is deliberately small (count-
    based, and each entry's device arrays scale with P_pad): a few recent
    grids cover the steady-state sweep/bench loop without pinning HBM for
    stale grids.
    """
    fast = np.frombuffer(fast_bytes, np.float32)
    slow = np.frombuffer(slow_bytes, np.float32)
    P = fast.shape[0]
    windows = _distinct_windows(np.concatenate([fast, slow]), "windows")
    # The SMA table is W-major ((N, W_pad, T_pad), T on lanes), so W pads
    # to a SUBLANE multiple (8) only — a 128-pad here would 4x the table
    # HBM, the per-cell table DMA (~17% of wall time per the roofline
    # accounting), and the MXU contraction width for small grids.
    W_pad = _round_up(max(windows.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)

    warm = np.zeros((1, P_pad), np.float32)
    warm[0, :P] = np.maximum(fast, slow)
    warm[0, P:] = 1.0
    # ONE difference selector (+1 fast row, -1 slow row) built host-side:
    # exact 0/±1 integers (identical to the in-kernel subtraction it
    # replaces), half the per-cell selector VMEM stream.
    oh_d = (_window_onehot(windows, fast, W_pad, P_pad)
            - _window_onehot(windows, slow, W_pad, P_pad))
    return (tuple(int(w) for w in windows), _const(oh_d), _const(warm))


# ---------------------------------------------------------------------------
# Momentum and Donchian fused kernels (T-minor tables, shared machinery)
# ---------------------------------------------------------------------------

# NOTE: channel/warmup fills use a finite 1e30 instead of +/-inf — an inf
# entry in a selection table would turn the one-hot MXU contraction into
# 0 * inf = NaN. Closes are ~1e2, so comparisons behave identically.


def _ema_rows(x, alpha: float):
    """EMA along the last axis with a scalar decay, as a shift-based
    doubling ladder (the prep-side twin of the in-kernel ``_ema_ladder``).

    Delegates to ``rolling.ema_ladder`` — the SAME function the generic
    models (MACD, TRIX) evaluate their EMAs with, which is what makes the
    fused and generic paths rounding twins (the parity fix that took MACD
    from 26/6400 verify flips to 2). Keep this a delegation, not a copy:
    a drifting twin silently reintroduces that flip class. (The ladder is
    also what makes compile time tractable: XLA compiles associative_scan's
    deep slice graph ~30x slower at the bench shape, and the remote-proxy
    backend cannot persistently cache compiles.)
    """
    from . import rolling
    return rolling.ema_ladder(x, alpha=jnp.float32(alpha))


def _mom_signal_tail(past_tbl, r, close, ol_ref, warm_ref, tr, out_ref, *,
                     cost: float, ppy: int, epilogue: str):
    """Shared momentum selection + metrics tail (both table substrates).

    The signal is exact — the past-close table holds raw close values, the
    one-hot contraction copies one of them per lane, and
    ``sign(close - past)`` involves no rounding at all."""
    T_pad = past_tbl.shape[1]
    dn = (((0,), (0,)), ((), ()))
    past = jax.lax.dot_general(past_tbl, ol_ref[:], dn,
                               preferred_element_type=jnp.float32,
                               precision=jax.lax.Precision.HIGHEST)

    lanes = ol_ref.shape[1]            # widest legal param block (launcher)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]     # lookback + 1
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    pos = jnp.where(valid, jnp.sign(close - past), 0.0)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


def _mom_kernel(r_ref, c_ref, past_ref, ol_ref, warm_ref, *refs,
                cost: float, ppy: int, T_real: int | None, epilogue: str):
    tr, out_ref = _unpack_tr(refs, T_real)
    _mom_signal_tail(past_ref[0], r_ref[0], c_ref[0], ol_ref, warm_ref, tr,
                     out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


def _mom_kernel_inline(r_ref, c_ref, crow_ref, ol_ref, warm_ref, *refs,
                       cost: float, ppy: int, T_real: int | None,
                       windows: tuple, W_pad: int, epilogue: str):
    """Momentum with the past-close table built in VMEM scratch.

    The XLA prep's table is a clipped gather ``close_p[max(t - w, 0)]``;
    here each distinct lookback's row is a lane-rotate of the close row
    with the wrapped region replaced by ``close_p[0]`` — the same values
    bit-for-bit (raw closes, no arithmetic), so this substrate is exact
    on every backend, unlike the SMA inline table's division caveat.
    Built once per ticker at param-block ``j == 0`` (see `_kernel_inline`
    for the scratch-persistence contract)."""
    *head, past_scr = refs
    tr, out_ref = _unpack_tr(tuple(head), T_real)
    T_pad = r_ref.shape[1]

    @pl.when(pl.program_id(1) == 0)
    def _build():
        crow = crow_ref[0]                                 # (1, T_pad)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, T_pad), 1)
        first = crow[:, :1]                                # clip-gather fill
        for k, w in enumerate(windows):
            w = int(w)
            if w < T_pad:
                row = jnp.where(lane >= w, _rot_lanes(crow, w), first)
            else:
                row = jnp.broadcast_to(first, crow.shape)
            past_scr[k:k + 1, :] = row
        for k in range(len(windows), W_pad):
            past_scr[k:k + 1, :] = jnp.zeros((1, T_pad), jnp.float32)

    _mom_signal_tail(past_scr[:], r_ref[0], c_ref[0], ol_ref, warm_ref, tr,
                     out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


def _don_latch_tail(sig_tbl, r, ow_ref, warm_ref, tr, out_ref, *,
                    cost: float, ppy: int, epilogue: str):
    """Shared Donchian breakout-sign selection + latch machine + metrics.

    The latch machine is a 3-state prefix composition (breakout latches
    the position until the opposite channel is touched — associative like
    the band machine, so the same log-depth ladder applies; mirrors
    ``models.donchian``'s lax.scan). The one-hot contraction copies exact
    values in {-1, 0, +1}, so thresholding at ±0.5 recovers the booleans
    exactly."""
    T_pad = sig_tbl.shape[1]
    dn = (((0,), (0,)), ((), ()))
    s = jax.lax.dot_general(sig_tbl, ow_ref[:], dn,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)
    up = s > 0.5
    down = s < -0.5

    lanes = ow_ref.shape[1]            # widest legal param block (launcher)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]     # window + 1
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    # Latch transition maps (up wins over down, else hold the prior state),
    # invalid bars force flat — models.donchian's scan body, vectorized.
    enter = lambda hold: jnp.where(up, 1.0, jnp.where(down, -1.0, hold))
    pm = jnp.where(valid, enter(-1.0), 0.0)
    p0 = jnp.where(valid, enter(0.0), 0.0)
    pp = jnp.where(valid, enter(1.0), 0.0)
    pos = _compose3_path(pm, p0, pp, epilogue)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


def _don_kernel(r_ref, c_ref, sig_ref, ow_ref, warm_ref, *refs,
                cost: float, ppy: int, T_real: int | None, epilogue: str):
    """Donchian cell over the XLA-built breakout-sign table.

    The per-(ticker, window) breakout sign (+1 above the prior channel
    high, -1 below the prior low, up wins) is precomputed in prep — ONE
    table and one selection matmul where separate high/low channel tables
    would need two of each. The close column (``c_ref``) is unused here;
    it rides the shared momentum/donchian plumbing
    (:func:`_single_window_pallas`)."""
    del c_ref
    tr, out_ref = _unpack_tr(refs, T_real)
    _don_latch_tail(sig_ref[0], r_ref[0], ow_ref, warm_ref, tr, out_ref,
                    cost=cost, ppy=ppy, epilogue=epilogue)


def _don_kernel_inline(r_ref, c_ref, crow_ref, hi_ref, lo_ref, ow_ref,
                       warm_ref, *refs, cost: float, ppy: int,
                       T_real: int | None, windows: tuple, W_pad: int,
                       epilogue: str):
    """Donchian with the breakout-sign table built in VMEM scratch.

    Rebuilds `_extrema_table`'s shared sparse-table range query in-kernel
    from the raw high/low rows — log2(max window) doubling levels once,
    then each window's channel is the max/min of two overlapping spans —
    and compares the close row against the 1-bar-shifted channels to form
    the ±1/0 sign rows. Max/min and comparisons of raw prices are exact,
    so this substrate matches the XLA-table path bit-for-bit on every
    backend (same algebra, same neutral fills). Built once per ticker at
    param-block ``j == 0`` (see `_kernel_inline` for the scratch
    contract)."""
    del c_ref
    *head, sig_scr = refs
    tr, out_ref = _unpack_tr(tuple(head), T_real)
    T_pad = r_ref.shape[1]

    @pl.when(pl.program_id(1) == 0)
    def _build():
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, T_pad), 1)

        def shifted_row(row, s: int, fill: float):
            # `_shift_t`'s semantics on a (1, T_pad) lane-major row.
            if s == 0:
                return row
            if s >= T_pad:
                return jnp.full_like(row, fill)
            return jnp.where(lane >= s, _rot_lanes(row, s), fill)

        def levels_of(src, op, neutral: float):
            max_k = max(int(w).bit_length() - 1 for w in windows)
            levels = [src]
            for k in range(max_k):
                levels.append(op(levels[k],
                                 shifted_row(levels[k], 1 << k, neutral)))
            return levels

        # Only the two log2(max window) level stacks stay live; each
        # window's channel combine + prior-bar shift + breakout compare
        # fuses into its own loop step. (Materializing all per-window
        # rows first OOMs VMEM stack: a (1, T_pad) row occupies a full
        # 8-sublane tile, so 2 x W live rows is ~16x the scratch size.)
        hi_levels = levels_of(hi_ref[0], jnp.maximum, float("-inf"))
        lo_levels = levels_of(lo_ref[0], jnp.minimum, float("inf"))
        crow = crow_ref[0]
        for k, w in enumerate(windows):
            w = int(w)
            kk = w.bit_length() - 1             # largest 2^kk <= w
            hi = jnp.maximum(hi_levels[kk],
                             shifted_row(hi_levels[kk], w - (1 << kk),
                                         float("-inf")))
            lo = jnp.minimum(lo_levels[kk],
                             shifted_row(lo_levels[kk], w - (1 << kk),
                                         float("inf")))
            hi = jnp.where(lane >= w - 1, hi, 1e30)
            lo = jnp.where(lane >= w - 1, lo, -1e30)
            hi_prev = shifted_row(hi, 1, 1e30)
            lo_prev = shifted_row(lo, 1, -1e30)
            sig_scr[k:k + 1, :] = jnp.where(
                crow >= hi_prev, 1.0,
                jnp.where(crow <= lo_prev, -1.0, 0.0))
        for k in range(len(windows), W_pad):
            sig_scr[k:k + 1, :] = jnp.zeros((1, T_pad), jnp.float32)

    _don_latch_tail(sig_scr[:], r_ref[0], ow_ref, warm_ref, tr, out_ref,
                    cost=cost, ppy=ppy, epilogue=epilogue)


def _single_window_pallas(kernel, close, tables, onehot_w, warm, t_real, *,
                          T_pad: int, W_pad: int, P_real: int,
                          T_real: int | None, interpret: bool,
                          aux_rows=(), scratch_shapes=(), lanes_cap=_LANES,
                          lanes_env: int = 0):
    """Shared pallas_call plumbing for the momentum/donchian kernels:
    returns + close columns, one or two (N, W_pad, T_pad) tables, the
    one-hot/warmup lanes, optional ragged lengths.

    ``aux_rows`` are extra ``(N, T_pad)`` series delivered to the kernel as
    ``(1, 1, T_pad)`` lane-major rows (T on lanes), and ``scratch_shapes``
    are forwarded to ``pallas_call`` — together they carry the in-kernel
    (VMEM-scratch) table builders, which take raw series rows instead of
    XLA-built ``(N, W_pad, T_pad)`` tables (see `_kernel_inline` for the
    pattern and the scratch-persistence contract).
    """
    N = close.shape[0]
    P_pad = onehot_w.shape[1]
    lanes = _widest_lanes(P_pad, lanes_cap, T_pad, lanes_env)
    n_blocks = P_pad // lanes
    table_specs = [
        pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM)
        for _ in tables
    ]
    aux_specs = [
        pl.BlockSpec((1, 1, T_pad), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM)
        for _ in aux_rows
    ]
    out = pl.pallas_call(
        kernel,
        grid=(N, n_blocks),
        in_specs=[
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ] + table_specs + aux_specs + [
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        scratch_shapes=list(scratch_shapes),
        interpret=interpret,
    )(_rets3(close), close[..., None], *tables,
      *(row[:, None, :] for row in aux_rows), onehot_w, warm,
      *_tr_args(t_real, T_real))
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "table", "lanes_env", "epilogue"))
def _fused_mom_call(close, onehot_l, warm, t_real, *, windows: tuple,
                    T_pad: int, W_pad: int, P_real: int, T_real: int | None,
                    cost: float, ppy: int, interpret: bool,
                    table: str = "inline", lanes_env: int = 0,
                    epilogue: str = _EPILOGUE_DEFAULT):
    """Past-close table prep + pallas call in one jit.

    ``table="hbm"``: the table is a single clipped XLA gather of raw
    closes — exact values, no arithmetic. ``table="inline"`` (default):
    the kernel rebuilds the same rows in VMEM scratch by lane-rotating the
    close row (`_mom_kernel_inline`) — bit-identical on every backend (no
    arithmetic either way), with no XLA gather and no table HBM stream.
    """
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    if table == "inline":
        kernel = functools.partial(_mom_kernel_inline, cost=cost, ppy=ppy,
                                   T_real=T_real, windows=windows,
                                   W_pad=W_pad, epilogue=epilogue)
        return _single_window_pallas(
            kernel, close_p, [], onehot_l, warm, t_real, T_pad=T_pad,
            W_pad=W_pad, P_real=P_real, T_real=T_real, interpret=interpret,
            aux_rows=[close_p],
            scratch_shapes=[pltpu.VMEM((W_pad, T_pad), jnp.float32)],
            lanes_cap=512, lanes_env=lanes_env)
    w_col = jnp.asarray(np.asarray(windows, np.int32))[:, None]  # (W,1)
    t_row = jnp.arange(T_pad)[None, :]
    gather_idx = jnp.clip(t_row - w_col, 0, T_pad - 1)           # (W,T_pad)
    past_tbl = _pad_w(jnp.take(close_p, gather_idx, axis=1), W_pad)
    kernel = functools.partial(_mom_kernel, cost=cost, ppy=ppy,
                               T_real=T_real, epilogue=epilogue)
    return _single_window_pallas(
        kernel, close_p, [past_tbl], onehot_l, warm, t_real, T_pad=T_pad,
        W_pad=W_pad, P_real=P_real, T_real=T_real, interpret=interpret,
        lanes_cap=512, lanes_env=lanes_env)


def _extrema_table(src_p, windows: tuple, mode: str, warm_fill: float):
    """All distinct-window rolling extrema of padded ``(N, T_pad)`` rows as
    one ``(N, W, T_pad)`` stack, via a SHARED sparse table.

    Per-window doubling ladders cost O(W · T log W) passes; instead build
    log2(max window) doubling levels once — ``level[k][t]`` covers
    ``x[t-2^k+1 .. t]`` — then every window is the max/min of TWO
    overlapping spans (the classic sparse-table range query). Max/min of
    raw prices either way: bit-identical to ``rolling.rolling_max``,
    ~6x fewer elementwise passes at the 125-distinct-window bench grid.
    Warmup bars (t < w-1) take ``warm_fill``.
    """
    op = jnp.maximum if mode == "max" else jnp.minimum
    neutral = float("-inf") if mode == "max" else float("inf")
    t_row = jnp.arange(src_p.shape[-1])[None, :]
    max_k = max((int(w)).bit_length() - 1 for w in windows)
    levels = [src_p]
    for k in range(max_k):
        levels.append(op(levels[k], _shift_t(levels[k], 1 << k, neutral)))
    rows = []
    for w in windows:
        w = int(w)
        k = w.bit_length() - 1                  # largest 2^k <= w
        row = op(levels[k], _shift_t(levels[k], w - (1 << k), neutral))
        rows.append(jnp.where(t_row >= w - 1, row, warm_fill))
    return jnp.stack(rows, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "table", "epilogue"))
def _fused_don_call(close, hi_src, lo_src, onehot_w, warm, t_real, *,
                    windows: tuple, T_pad: int, W_pad: int, P_real: int,
                    T_real: int | None, cost: float, ppy: int,
                    interpret: bool, table: str = "hbm",
                    epilogue: str = _EPILOGUE_DEFAULT):
    """Channel-extrema table prep + pallas call in one jit. Windows are
    static, so all distinct windows' rolling max/min come from one shared
    sparse table (:func:`_extrema_table`); max/min of exact prices is
    exact, so the channel — and hence every breakout comparison — matches
    the generic path bit-for-bit.

    ``hi_src``/``lo_src`` are the columns the channel extrema come from:
    the close itself for the close-only variant, the HIGH/LOW columns for
    the classic channel (``models.donchian._positions_hl``). ±1e30 stands
    in for the generic path's ±inf warmup fill; the channel values are
    consumed only by prep-side comparisons here (the kernel sees the
    finite sign table), and no finite price ever clears 1e30, so every
    breakout comparison is identical.

    ``table="inline"`` skips the XLA tables entirely: the kernel rebuilds
    the same sparse-table range query and breakout comparisons in VMEM
    scratch (`_don_kernel_inline`) — bit-identical on every backend
    (max/min and compares of raw prices are exact both ways). It measured
    a wash on-chip, so the shipped default stays ``"hbm"``
    (DESIGN.md "In-kernel table construction")."""
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    if table == "inline":
        kernel = functools.partial(_don_kernel_inline, cost=cost, ppy=ppy,
                                   T_real=T_real, windows=windows,
                                   W_pad=W_pad, epilogue=epilogue)
        return _single_window_pallas(
            kernel, close_p, [], onehot_w, warm, t_real,
            T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
            interpret=interpret,
            aux_rows=[close_p, _pad_last(hi_src, T_pad),
                      _pad_last(lo_src, T_pad)],
            scratch_shapes=[pltpu.VMEM((W_pad, T_pad), jnp.float32)],
            lanes_cap=256)
    hi_tbl = _extrema_table(_pad_last(hi_src, T_pad), windows, "max", 1e30)
    lo_tbl = _extrema_table(_pad_last(lo_src, T_pad), windows, "min", -1e30)
    # Channel known at the close of t-1, applied to bar t; collapsing both
    # channel tables into ONE breakout-sign table (+1 above the prior
    # high, -1 below the prior low, up wins — the latch's exact
    # precedence) halves the per-cell table traffic and selection matmuls.
    hi_prev = _shift_t(hi_tbl, 1, 1e30)
    lo_prev = _shift_t(lo_tbl, 1, -1e30)
    c3 = close_p[:, None, :]
    sig_tbl = _pad_w(jnp.where(c3 >= hi_prev, 1.0,
                               jnp.where(c3 <= lo_prev, -1.0, 0.0)), W_pad)
    kernel = functools.partial(_don_kernel, cost=cost, ppy=ppy,
                               T_real=T_real, epilogue=epilogue)
    return _single_window_pallas(
        kernel, close_p, [sig_tbl], onehot_w, warm, t_real,
        T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
        interpret=interpret, lanes_cap=256)


def _resolve_table(table: str | None, env_var: str, default: str,
                   tuned_key: str | None = None) -> str:
    """Shared table-substrate knob: explicit arg > per-family env > tuned
    schedule > default.

    ``"inline"`` builds the window table in VMEM scratch inside the kernel;
    ``"hbm"`` streams the XLA-built table (kept as the A/B twin). An
    invalid tuned value degrades to the default instead of raising."""
    if table is None:
        table = os.environ.get(env_var)
        if table is None:
            tuned = _tuned_value(tuned_key) if tuned_key else None
            table = tuned if tuned in ("inline", "hbm") else default
    if table not in ("inline", "hbm"):
        raise ValueError(f"table must be 'inline' or 'hbm', got {table!r}")
    return table


# (env var, shipped default) per table-substrate family; donchian stays
# "hbm" by measurement (the inline rebuild A/B'd a wash on-chip — DESIGN.md
# "In-kernel table construction").
_TABLE_FAMILIES = {
    "sma": ("DBX_SMA_TABLE", "inline"),
    "boll": ("DBX_BOLL_TABLE", "inline"),
    "mom": ("DBX_MOM_TABLE", "inline"),
    "don": ("DBX_DON_TABLE", "hbm"),
    "obv": ("DBX_OBV_TABLE", "inline"),
}


def _family_table(family: str, table: str | None) -> str:
    """Resolve a wrapper's table substrate from the single source of truth.

    Every sweep wrapper with a table knob MUST route through this (not a
    literal (env, default) pair) so ``substrate_defaults()`` /
    ``route_substrates()`` — and the observability surfaces built on them —
    can never report a different substrate than the kernel serves."""
    return _resolve_table(table, *_TABLE_FAMILIES[family],
                          tuned_key=f"table_{family}")

# Strategy name (rpc.compute registry key) -> table family, for the route
# substrate counters. Strategies without an in-kernel table substrate
# always stream the XLA-built table ("hbm", no knob).
_STRATEGY_TABLE_FAMILY = {
    "sma_crossover": "sma",
    "bollinger": "boll",
    "bollinger_touch": "boll",
    "momentum": "mom",
    "donchian": "don",
    "donchian_hl": "don",
    "obv_trend": "obv",
}


def substrate_defaults() -> dict:
    """The live (env-resolved) kernel substrate defaults, host-side.

    One stop for observability surfaces — the worker backend publishes
    this as the ``dbx_fused_substrate_info`` gauge labels so a fleet
    operator can read per-worker which epilogue / table / lane-block
    substrate is serving without grepping logs (GetStats ``obs_json``,
    ``/stats.json``, ``obs.dump``). Raises on invalid env values — the
    same validation the sweep call would hit, surfaced at backend start.
    """
    out = {"epilogue": _resolve_epilogue(None),
           "lanes_cap": str(resolve_lanes_cap())}
    for fam, (env_var, default) in _TABLE_FAMILIES.items():
        out[f"table_{fam}"] = _resolve_table(None, env_var, default,
                                             tuned_key=f"table_{fam}")
    return out


def route_substrates(strategy: str) -> dict:
    """``{"epilogue": ..., "table": ...}`` the named strategy's sweep would
    run under right now (env-resolved defaults) — the label set for the
    per-group ``dbx_fused_substrate_total`` route counter."""
    fam = _STRATEGY_TABLE_FAMILY.get(strategy)
    table = ("hbm" if fam is None else _family_table(fam, None))
    return {"epilogue": _resolve_epilogue(None), "table": table}


def fused_momentum_sweep(close, lookback, *, t_real=None, cost: float = 0.0,
                         periods_per_year: int = 252,
                         interpret: bool | None = None,
                         table: str | None = None,
                         epilogue: str | None = None,
                         carry_out: bool = False) -> Metrics:
    """Fused time-series momentum sweep: ``(N, T)`` closes x ``(P,)`` lanes.

    Matches ``run_sweep(..., "momentum")`` with an *exact* signal (the
    past-close selection involves no arithmetic); metrics carry the usual
    f32 reduction tolerance. ``table`` picks the past-close-table substrate
    (env ``DBX_MOM_TABLE``): both are exact, see :func:`_fused_mom_call`.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    lookback = np.asarray(lookback)
    T = close.shape[1]
    windows, onehot_l, warm = _single_window_grid_setup(
        lookback.astype(np.float32).tobytes(), 1.0, "lookbacks")
    m = _fused_mom_call(close, onehot_l, warm, _t_real_col(t_real, close),
                        windows=windows, T_pad=_round_up(T, 128),
                        W_pad=onehot_l.shape[0], P_real=lookback.shape[0],
                        T_real=T if t_real is None else None,
                        cost=float(cost), ppy=int(periods_per_year),
                        interpret=bool(interpret),
                        table=_family_table("mom", table),
                        lanes_env=resolve_lanes_cap(),
                        epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "momentum", {"close": close},
                           {"lookback": lookback}, t_real=t_real,
                           cost=cost, ppy=periods_per_year,
                           epilogue=epilogue)


def fused_donchian_sweep(close, window, *, t_real=None, cost: float = 0.0,
                         periods_per_year: int = 252,
                         interpret: bool | None = None,
                         table: str | None = None,
                         epilogue: str | None = None,
                         carry_out: bool = False) -> Metrics:
    """Fused Donchian-breakout sweep: ``(N, T)`` closes x ``(P,)`` lanes.

    Matches ``run_sweep(..., "donchian")``: the channel extrema are exact
    (max/min of raw closes), so breakout comparisons and the latch path are
    bit-identical to the generic scan; metrics carry f32 tolerance.
    ``table`` picks the sign-table substrate (env ``DBX_DON_TABLE``): both
    are exact, see :func:`_fused_don_call`.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    window = np.asarray(window)
    T = close.shape[1]
    windows, onehot_w, warm = _single_window_grid_setup(
        window.astype(np.float32).tobytes(), 1.0, "windows")
    m = _fused_don_call(close, close, close, onehot_w, warm,
                        _t_real_col(t_real, close),
                        windows=windows, T_pad=_round_up(T, 128),
                        W_pad=onehot_w.shape[0], P_real=window.shape[0],
                        T_real=T if t_real is None else None,
                        cost=float(cost), ppy=int(periods_per_year),
                        interpret=bool(interpret),
                        table=_family_table("don", table),
                        epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "donchian", {"close": close},
                           {"window": window}, t_real=t_real, cost=cost,
                           ppy=periods_per_year, epilogue=epilogue)


def fused_donchian_hl_sweep(close, high, low, window, *, t_real=None,
                            cost: float = 0.0, periods_per_year: int = 252,
                            interpret: bool | None = None,
                            table: str | None = None,
                            epilogue: str | None = None,
                            carry_out: bool = False) -> Metrics:
    """Fused high/low-channel Donchian sweep: ``(N, T)`` panels x ``(P,)``.

    Matches ``run_sweep(..., "donchian_hl")`` — breakout when the close
    clears the trailing extreme of the *highs*/*lows* (the classic channel;
    the first fused kernel consuming the high/low columns). Channel extrema
    are exact, so breakouts and the latch path are bit-identical to the
    generic scan; metrics carry f32 tolerance.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    high = jnp.asarray(high, jnp.float32)
    low = jnp.asarray(low, jnp.float32)
    window = np.asarray(window)
    T = close.shape[1]
    windows, onehot_w, warm = _single_window_grid_setup(
        window.astype(np.float32).tobytes(), 1.0, "windows")
    m = _fused_don_call(close, high, low, onehot_w, warm,
                        _t_real_col(t_real, close),
                        windows=windows, T_pad=_round_up(T, 128),
                        W_pad=onehot_w.shape[0], P_real=window.shape[0],
                        T_real=T if t_real is None else None,
                        cost=float(cost), ppy=int(periods_per_year),
                        interpret=bool(interpret),
                        table=_family_table("don", table),
                        epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(
        m, "donchian_hl", {"close": close, "high": high, "low": low},
        {"window": window}, t_real=t_real, cost=cost,
        ppy=periods_per_year, epilogue=epilogue)


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_stoch_call(close, high, low, onehot_w, band_lanes, warm, t_real,
                      *, windows: tuple, T_pad: int, W_pad: int, P_real: int,
                      T_real: int | None, cost: float, ppy: int,
                      interpret: bool, epilogue: str = _EPILOGUE_DEFAULT):
    """%K table prep + the *Bollinger* kernel: the centered stochastic
    oscillator is just another z-score feeding the shared band machine
    (enter beyond ±band, exit at the 50 centerline: z_exit = 0).

    Channel extrema come from the shared sparse table
    (:func:`_extrema_table`) over the HIGH/LOW columns — exact max/min, so
    %K sees bit-identical channel values to the generic
    ``models.stochastic`` path; the %K arithmetic replicates
    ``stochastic_k``'s float op order (flat channels fall back to the
    neutral 50)."""
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    hi_tbl = _extrema_table(_pad_last(high, T_pad), windows, "max", 1e30)
    lo_tbl = _extrema_table(_pad_last(low, T_pad), windows, "min", -1e30)
    rng = hi_tbl - lo_tbl
    k_tbl = jnp.where(
        rng > _EPS,
        100.0 * (close_p[:, None, :] - lo_tbl) / (rng + _EPS),
        50.0) - 50.0
    w_col = jnp.asarray(np.asarray(windows, np.int32))[:, None]  # (W,1)
    t_row = jnp.arange(T_pad)[None, :]
    z_table = _pad_w(jnp.where((t_row >= w_col - 1)[None], k_tbl, 0.0),
                     W_pad)
    kernel = functools.partial(_boll_kernel, cost=cost, ppy=ppy,
                               z_exit=0.0, T_real=T_real, epilogue=epilogue)
    return _band_machine_pallas(
        kernel, close_p, z_table, onehot_w, band_lanes, warm, t_real,
        T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
        interpret=interpret)


def fused_stochastic_sweep(close, high, low, window, band, *, t_real=None,
                           cost: float = 0.0, periods_per_year: int = 252,
                           interpret: bool | None = None,
                           epilogue: str | None = None,
                           carry_out: bool = False) -> Metrics:
    """Fused stochastic-%K reversion sweep: ``(N, T)`` panels x ``(P,)``.

    ``window``/``band`` are flat per-combo arrays (:func:`product_grid`
    order); windows must be integral bar counts. Matches
    ``run_sweep(..., "stochastic")`` (``models.stochastic``): bit-level on
    CPU interpret mode; the usual MXU knife-edge caveat on TPU. The second
    fused kernel consuming the high/low columns (after the HL-Donchian).
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    high = jnp.asarray(high, jnp.float32)
    low = jnp.asarray(low, jnp.float32)
    window = np.asarray(window)
    band = np.asarray(band, np.float32)
    T = close.shape[1]

    # _boll_grid_setup's shapes fit exactly: warm = window, band lanes in
    # the k slot (padded lanes get band = +inf and never enter).
    windows, onehot_w, band_lanes, warm = _boll_grid_setup(
        window.astype(np.float32).tobytes(), band.tobytes())
    m = _fused_stoch_call(close, high, low, onehot_w, band_lanes, warm,
                          _t_real_col(t_real, close),
                          windows=windows, T_pad=_round_up(T, 128),
                          W_pad=onehot_w.shape[0],
                          P_real=window.shape[0],
                          T_real=T if t_real is None else None,
                          cost=float(cost), ppy=int(periods_per_year),
                          interpret=bool(interpret),
                          epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(
        m, "stochastic", {"close": close, "high": high, "low": low},
        {"window": window, "band": band}, t_real=t_real, cost=cost,
        ppy=periods_per_year, epilogue=epilogue)


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_keltner_call(close, high, low, onehot_w, k_lanes, warm, t_real,
                        *, windows: tuple, T_pad: int, W_pad: int,
                        P_real: int, T_real: int | None, cost: float,
                        ppy: int, interpret: bool,
                        epilogue: str = _EPILOGUE_DEFAULT):
    """Keltner z-table prep + the *Bollinger* kernel: the ATR-normalized
    deviation from the EMA midline feeds the shared band machine (enter
    beyond ±k ATRs, exit at the midline re-cross: z_exit = 0).

    Per distinct window: the EMA midline runs as the shift-ladder
    (``_ema_rows`` — float-order differs from the generic
    ``associative_scan`` EMA, the RSI/MACD caveat) and the ATR is a
    cumsum-difference windowed mean of the true range. Warmup rows — where
    the generic path's NaN-filled rolling mean makes ``atr > eps`` False
    and the deviation falls back to exactly 0 — are forced to 0, as is the
    zero-ATR (constant-price) fallback."""
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    high_p = _pad_last(high, T_pad)
    low_p = _pad_last(low, T_pad)
    w_col, w_f, t_row, windowed_sum, _ = _cumsum_window_tools(windows, T_pad)

    prev_close = jnp.concatenate([close_p[:, :1], close_p[:, :-1]], axis=-1)
    tr = jnp.maximum(high_p - low_p,
                     jnp.maximum(jnp.abs(high_p - prev_close),
                                 jnp.abs(low_p - prev_close)))
    atr = windowed_sum(tr) / w_f                                 # (N,W,T_pad)
    mids = jnp.stack(
        [_ema_rows(close_p, 2.0 / (float(w) + 1.0)) for w in windows],
        axis=1)
    dev = close_p[:, None, :] - mids
    have = (t_row >= (w_col - 1))[None] & (atr > _EPS)
    z_table = _pad_w(jnp.where(have, dev / (atr + _EPS), 0.0), W_pad)

    kernel = functools.partial(_boll_kernel, cost=cost, ppy=ppy,
                               z_exit=0.0, T_real=T_real, epilogue=epilogue)
    return _band_machine_pallas(
        kernel, close_p, z_table, onehot_w, k_lanes, warm, t_real,
        T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
        interpret=interpret)


def fused_keltner_sweep(close, high, low, window, k, *, t_real=None,
                        cost: float = 0.0, periods_per_year: int = 252,
                        interpret: bool | None = None,
                        epilogue: str | None = None,
                        carry_out: bool = False) -> Metrics:
    """Fused Keltner-channel reversion sweep: ``(N, T)`` panels x ``(P,)``.

    ``window``/``k`` are flat per-combo arrays (:func:`product_grid`
    order); windows must be integral bar counts. Matches
    ``run_sweep(..., "keltner")`` (``models.keltner``) to f32 tolerance
    (the in-prep EMA ladder rounds differently from the generic
    ``associative_scan`` — the RSI/MACD caveat — so knife-edge midline
    crossings can resolve differently; quantified by ``bench.py
    --verify``).
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    high = jnp.asarray(high, jnp.float32)
    low = jnp.asarray(low, jnp.float32)
    window = np.asarray(window)
    k = np.asarray(k, np.float32)
    T = close.shape[1]

    windows, onehot_w, k_lanes, warm = _boll_grid_setup(
        window.astype(np.float32).tobytes(), k.tobytes())
    m = _fused_keltner_call(close, high, low, onehot_w, k_lanes, warm,
                            _t_real_col(t_real, close),
                            windows=windows, T_pad=_round_up(T, 128),
                            W_pad=onehot_w.shape[0],
                            P_real=window.shape[0],
                            T_real=T if t_real is None else None,
                            cost=float(cost), ppy=int(periods_per_year),
                            interpret=bool(interpret),
                            epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(
        m, "keltner", {"close": close, "high": high, "low": low},
        {"window": window, "k": k}, t_real=t_real, cost=cost,
        ppy=periods_per_year, epilogue=epilogue)


@functools.lru_cache(maxsize=8)
def _single_window_grid_setup(vals_bytes: bytes, warm_offset: float,
                              what: str):
    """Distinct windows + one-hot/warmup lanes for single-window-axis
    strategies (momentum, donchian). ``warm = value + warm_offset``."""
    vals = np.frombuffer(vals_bytes, np.float32)
    P = vals.shape[0]
    windows = _distinct_windows(vals, what)
    W_pad = _round_up(max(windows.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    oh = _window_onehot(windows, vals, W_pad, P_pad)
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = vals + warm_offset
    return (tuple(int(w) for w in windows), _const(oh),
            _const(warm))


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_rsi_call(close, onehot_p, band_lanes, warm, t_real, *,
                    windows: tuple, T_pad: int, W_pad: int, P_real: int,
                    T_real: int | None, cost: float, ppy: int,
                    interpret: bool, epilogue: str = _EPILOGUE_DEFAULT):
    """RSI table prep + the *Bollinger* kernel: ``rsi - 50`` is just another
    z-score feeding the shared band machine (enter beyond ±band, exit at the
    centerline), so the whole kernel is reused verbatim with z_exit=0.

    Each distinct period's Wilder EMA (static alpha = 1/period) runs as the
    shift-ladder (``_ema_rows``) over ``(N, T_pad)`` —
    ``models.rsi.rsi_index``'s formula per window, float-order modulo the
    scan algorithm.
    """
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    diff = jnp.diff(close_p, axis=-1, prepend=close_p[..., :1])
    gains = jnp.maximum(diff, 0.0)
    losses = jnp.maximum(-diff, 0.0)
    # Per-distinct-period EMAs via the shift-ladder (see _ema_rows: the
    # associative_scan version compiled ~30x slower with no runtime win; a
    # batched (W, N, T_pad) scan was also slower on chip).
    rows = []
    for p_ in windows:
        alpha = 1.0 / float(p_)
        ag = _ema_rows(gains, alpha)
        al = _ema_rows(losses, alpha)
        rsi = 100.0 - 100.0 / (1.0 + ag / (al + 1e-12))
        rows.append(rsi - 50.0)
    z_tbl = _pad_w(jnp.stack(rows, axis=1), W_pad)               # (N,W,T_pad)

    kernel = functools.partial(_boll_kernel, cost=cost, ppy=ppy,
                               z_exit=0.0, T_real=T_real, epilogue=epilogue)
    return _band_machine_pallas(
        kernel, close_p, z_tbl, onehot_p, band_lanes, warm, t_real,
        T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
        interpret=interpret)


def fused_rsi_sweep(close, period, band, *, t_real=None, cost: float = 0.0,
                    periods_per_year: int = 252,
                    interpret: bool | None = None,
                    epilogue: str | None = None,
                    carry_out: bool = False) -> Metrics:
    """Fused RSI mean-reversion sweep: ``(N, T)`` closes x ``(P,)`` lanes.

    ``period``/``band`` are flat per-combo arrays (:func:`product_grid`
    order); periods must be integral bar counts. Matches
    ``run_sweep(..., "rsi")`` (``models.rsi``) to f32 tolerance.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    period = np.asarray(period)
    band = np.asarray(band, np.float32)
    T = close.shape[1]
    windows, onehot_p, band_lanes, warm = _rsi_grid_setup(
        period.astype(np.float32).tobytes(), band.tobytes())
    m = _fused_rsi_call(close, onehot_p, band_lanes, warm,
                        _t_real_col(t_real, close),
                        windows=windows, T_pad=_round_up(T, 128),
                        W_pad=onehot_p.shape[0], P_real=period.shape[0],
                        T_real=T if t_real is None else None,
                        cost=float(cost), ppy=int(periods_per_year),
                        interpret=bool(interpret),
                        epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "rsi", {"close": close},
                           {"period": period, "band": band}, t_real=t_real,
                           cost=cost, ppy=periods_per_year,
                           epilogue=epilogue)


@functools.lru_cache(maxsize=4)
def _rsi_grid_setup(period_bytes: bytes, band_bytes: bytes):
    """Distinct periods + one-hot/band/warmup lanes (warm = period + 1)."""
    period = np.frombuffer(period_bytes, np.float32)
    band = np.frombuffer(band_bytes, np.float32)
    P = period.shape[0]
    windows = _distinct_windows(period, "periods")
    W_pad = _round_up(max(windows.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    oh = _window_onehot(windows, period, W_pad, P_pad)
    band_lanes = np.full((1, P_pad), np.float32(np.inf))
    band_lanes[0, :P] = band      # padded lanes never enter (band = +inf)
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = period + 1.0    # models.rsi: valid_mask(T, period + 1)
    return (tuple(int(w) for w in windows), _const(oh),
            _const(band_lanes), _const(warm))


def _ema_ladder(x, a):
    """Per-lane EMA over the sublane axis: ``y[t] = (1-a)*y[t-1] + a*x[t]``
    with ``y[0] = x[0]`` and a per-lane decay ``a`` ((1, 128) or scalar).

    The first-order recurrence is associative under
    ``(A2,B2) ∘ (A1,B1) = (A1*A2, A2*B1 + B2)``, so it evaluates as a
    log-depth doubling ladder — the in-kernel analogue of
    ``ops.rolling.ema``'s associative_scan, needed here because the decay
    varies per *lane* (each param lane has its own span).
    """
    T_pad = x.shape[0]
    t0 = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) == 0
    A = jnp.where(t0, 0.0, jnp.broadcast_to(1.0 - a, x.shape))
    B = jnp.where(t0, x, a * x)
    span = 1
    while span < T_pad:
        Ae = _shift_down(A, span, 1.0)   # identity element (A=1, B=0)
        Be = _shift_down(B, span, 0.0)
        A, B = Ae * A, A * Be + B
        span *= 2
    return B


def _macd_kernel(r_ref, ema_ref, od_ref, asig_ref, warm_ref, *refs,
                 cost: float, ppy: int, T_real: int | None, epilogue: str):
    """MACD cell: one span-table selection gives the macd line; the signal
    line is a per-lane EMA (decay = 2/(signal_span+1)) evaluated with the
    in-kernel associative ladder; position = sign(macd - signal)."""
    tr, out_ref = _unpack_tr(refs, T_real)
    T_pad = r_ref.shape[1]
    r = r_ref[0]
    dn = (((0,), (0,)), ((), ()))
    # Difference one-hot (+1 fast row, -1 slow row), built HOST-side like
    # the SMA selector: one matmul yields the macd line directly — half
    # the MXU work and selector stream of separate f/s selections.
    macd = jax.lax.dot_general(ema_ref[0], od_ref[:], dn,
                               preferred_element_type=jnp.float32,
                               precision=jax.lax.Precision.HIGHEST)
    a_sig = asig_ref[0, :][None, :]                  # (1, lanes)
    sig = _ema_ladder(macd, a_sig)

    lanes = od_ref.shape[1]          # widest legal param block (launcher)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]                   # slow + signal - 1
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    pos = jnp.where(valid, jnp.sign(macd - sig), 0.0)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


@functools.partial(
    jax.jit,
    static_argnames=("spans", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_macd_call(close, onehot_d, a_sig, warm, t_real, *,
                     spans: tuple, T_pad: int, W_pad: int, P_real: int,
                     T_real: int | None, cost: float, ppy: int,
                     interpret: bool, epilogue: str = _EPILOGUE_DEFAULT):
    """Distinct-span EMA table prep + pallas call in one jit.

    The EMA table is built from the *demeaned* close — ``macd`` is
    shift-invariant (``models.macd``, which demeans identically), and the
    demeaned series keeps the f32 error proportional to price deviations
    rather than price level. Returns still come from the raw series.
    """
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    N = close.shape[0]
    close_dm = close_p - close_p[..., :1]
    rows = [_ema_rows(close_dm, 2.0 / (float(s) + 1.0)) for s in spans]
    ema_tbl = jnp.stack(rows, axis=1)                            # (N,W,T_pad)
    if W_pad > len(spans):
        ema_tbl = jnp.concatenate(
            [ema_tbl, jnp.zeros((N, W_pad - len(spans), T_pad),
                                jnp.float32)], axis=1)

    P_pad = a_sig.shape[1]
    # 256-lane cap: the per-lane signal-EMA ladder keeps several
    # (T_pad, lanes) arrays live (same budget class as the band machines).
    lanes = _widest_lanes(P_pad, 256)
    n_blocks = P_pad // lanes
    kernel = functools.partial(_macd_kernel, cost=cost, ppy=ppy,
                               T_real=T_real, epilogue=epilogue)
    out = pl.pallas_call(
        kernel,
        grid=(N, n_blocks),
        in_specs=[
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        interpret=interpret,
    )(_rets3(close_p), ema_tbl, onehot_d, a_sig, warm,
      *_tr_args(t_real, T_real))
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


def fused_macd_sweep(close, fast, slow, signal, *, t_real=None,
                     cost: float = 0.0, periods_per_year: int = 252,
                     interpret: bool | None = None,
                     epilogue: str | None = None,
                     carry_out: bool = False) -> Metrics:
    """Fused MACD signal-line crossover sweep: ``(N, T)`` x ``(P,)`` lanes.

    ``fast``/``slow``/``signal`` are flat per-combo span arrays
    (:func:`product_grid` order); spans must be integral. Matches
    ``run_sweep(..., "macd")`` (``models.macd``) to f32 tolerance — both
    paths demean the close and evaluate every EMA with the same
    shift-doubling ladder (``rolling.ema_ladder`` generically, ``_ema_rows``
    / ``_ema_ladder`` here), so they are rounding twins; the only residual
    divergence class is the MXU selection matmul for the macd line.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    fast = np.asarray(fast)
    slow = np.asarray(slow)
    signal = np.asarray(signal)
    T = close.shape[1]
    spans, onehot_d, a_sig, warm = _macd_grid_setup(
        fast.astype(np.float32).tobytes(),
        slow.astype(np.float32).tobytes(),
        signal.astype(np.float32).tobytes())
    m = _fused_macd_call(close, onehot_d, a_sig, warm,
                         _t_real_col(t_real, close),
                         spans=spans, T_pad=_round_up(T, 128),
                         W_pad=onehot_d.shape[0], P_real=fast.shape[0],
                         T_real=T if t_real is None else None,
                         cost=float(cost), ppy=int(periods_per_year),
                         interpret=bool(interpret),
                         epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(
        m, "macd", {"close": close},
        {"fast": fast, "slow": slow, "signal": signal}, t_real=t_real,
        cost=cost, ppy=periods_per_year, epilogue=epilogue)


@functools.lru_cache(maxsize=4)
def _macd_grid_setup(fast_bytes: bytes, slow_bytes: bytes,
                     signal_bytes: bytes):
    """Distinct spans (fast ∪ slow) + selectors, per-lane signal decay and
    warmup (= slow + signal - 1, ``models.macd``'s rule)."""
    fast = np.frombuffer(fast_bytes, np.float32)
    slow = np.frombuffer(slow_bytes, np.float32)
    signal = np.frombuffer(signal_bytes, np.float32)
    P = fast.shape[0]
    spans = _distinct_windows(np.concatenate([fast, slow]), "spans")
    _distinct_windows(signal, "signal spans")   # validate integrality only
    W_pad = _round_up(max(spans.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    # ONE difference selector (+1 fast row, -1 slow row), the SMA
    # `_grid_setup` discipline: exact 0/±1 integers, half the stream.
    oh_d = (_window_onehot(spans, fast, W_pad, P_pad)
            - _window_onehot(spans, slow, W_pad, P_pad))
    a_sig = np.zeros((1, P_pad), np.float32)
    a_sig[0, :P] = 2.0 / (signal + 1.0)
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = slow + signal - 1.0
    return (tuple(int(s) for s in spans), _const(oh_d),
            _const(a_sig), _const(warm))


def _obv_signal_tail(sma_tbl, r, obv, oh_ref, warm_ref, tr, out_ref, *,
                     cost: float, ppy: int, epilogue: str):
    """Shared OBV selection + metrics tail (both table substrates).

    One window-table selection gives the OBV rolling mean; position =
    ``sign(obv - sma)``. The W-major ``(W_pad, T_pad)`` table contracts
    its leading window axis (the SMA kernel's layout — a T-major/W-minor
    table pads W up to 128 lanes, a 12.8x HBM blow-up class this file
    keeps re-learning). The selection one-hot has a single nonzero per
    lane, so the MXU contraction is an exact copy — the only rounding in
    the cell is the subtraction itself."""
    T_pad = sma_tbl.shape[1]
    dn = (((0,), (0,)), ((), ()))
    sma = jax.lax.dot_general(sma_tbl, oh_ref[:], dn,
                              preferred_element_type=jnp.float32,
                              precision=jax.lax.Precision.HIGHEST)
    lanes = oh_ref.shape[1]          # widest legal param block (launcher)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    warm = warm_ref[0, :][None, :]               # (1, lanes) = window
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    pos = jnp.where(valid, jnp.sign(obv - sma), 0.0)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


def _obv_kernel(r_ref, obv_ref, sma_ref, oh_ref, warm_ref, *refs,
                cost: float, ppy: int, T_real: int | None, epilogue: str):
    tr, out_ref = _unpack_tr(refs, T_real)
    _obv_signal_tail(sma_ref[0], r_ref[0], obv_ref[0], oh_ref, warm_ref,
                     tr, out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


def _obv_kernel_inline(r_ref, obv_ref, cs_ref, oh_ref, warm_ref, *refs,
                       cost: float, ppy: int, T_real: int | None,
                       windows: tuple, W_pad: int, epilogue: str):
    """OBV with the SMA-of-OBV table built in VMEM scratch from the OBV
    cumsum row (`_build_sma_scratch` — the SMA kernel's builder on a
    different series). Same division-lowering caveat as the SMA inline
    substrate (`_kernel_inline`): bit-identical on CPU, 1-ULP table
    rounding possible on TPU, gated by the same verify budgets."""
    *head, sma_scr = refs
    tr, out_ref = _unpack_tr(tuple(head), T_real)

    @pl.when(pl.program_id(1) == 0)
    def _build():
        _build_sma_scratch(cs_ref[0], sma_scr, windows, W_pad)

    _obv_signal_tail(sma_scr[:], r_ref[0], obv_ref[0], oh_ref, warm_ref,
                     tr, out_ref, cost=cost, ppy=ppy, epilogue=epilogue)


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "table", "lanes_env", "epilogue"))
def _fused_obv_call(close, volume, onehot_w, warm, t_real, *,
                    windows: tuple, T_pad: int, W_pad: int, P_real: int,
                    T_real: int | None, cost: float, ppy: int,
                    interpret: bool, table: str = "hbm",
                    lanes_env: int = 0, epilogue: str = _EPILOGUE_DEFAULT):
    """OBV series + distinct-window SMA table prep + pallas call in one jit.

    The OBV accumulator is the SHARED ``rolling.obv_series`` (the same
    function ``models.obv`` evaluates), and the windowed mean follows the
    generic ``rolling.rolling_mean``'s cumsum-difference op order, so the
    paths are rounding twins by construction (see the SMA table comment in
    ``_fused_call`` for the gather layout rationale).
    """
    from . import rolling

    N, T = close.shape
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    vol_p = _pad_last(volume, T_pad)
    obv = rolling.obv_series(close_p, vol_p)                   # (N, T_pad)

    P_pad = onehot_w.shape[1]
    # sign kernel: no compose ladder
    lanes = _widest_lanes(P_pad, 512, T_pad, lanes_env)
    n_blocks = P_pad // lanes
    if table == "inline":
        cs = jnp.cumsum(obv, axis=1)[:, None, :]               # (N,1,T_pad)
        kernel = functools.partial(_obv_kernel_inline, cost=cost, ppy=ppy,
                                   T_real=T_real, windows=windows,
                                   W_pad=W_pad, epilogue=epilogue)
        table_arg = cs
        table_spec = pl.BlockSpec((1, 1, T_pad), lambda i, j: (i, 0, 0),
                                  memory_space=pltpu.VMEM)
        scratch = [pltpu.VMEM((W_pad, T_pad), jnp.float32)]
    else:
        # W-major SMA table of the OBV series — `_sma_table` on a
        # different input row (same cumsum-difference op order as the
        # generic rolling mean). The previous T-major (N, T_pad, W)
        # layout padded W up to 128 lanes per intermediate; its static-
        # shift prep materialized W lane-minor (N, T_pad, 1) rows — a
        # 12.8x-class HBM blow-up that OOM'd at 500 tickers.
        kernel = functools.partial(_obv_kernel, cost=cost, ppy=ppy,
                                   T_real=T_real, epilogue=epilogue)
        table_arg = _sma_table(obv, windows, W_pad)
        table_spec = pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                                  memory_space=pltpu.VMEM)
        scratch = []
    out = pl.pallas_call(
        kernel,
        grid=(N, n_blocks),
        in_specs=[
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            table_spec,
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(_rets3(close_p), obv[:, :, None], table_arg, onehot_w, warm,
      *_tr_args(t_real, T_real))
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


def fused_obv_sweep(close, volume, window, *, t_real=None, cost: float = 0.0,
                    periods_per_year: int = 252,
                    interpret: bool | None = None,
                    table: str | None = None,
                    epilogue: str | None = None,
                    carry_out: bool = False) -> Metrics:
    """Fused OBV-trend sweep: ``(N, T)`` closes+volumes x ``(P,)`` windows.

    ``window`` is a flat per-combo window array (:func:`product_grid`
    order); windows must be integral bar counts. Matches
    ``run_sweep(..., "obv_trend")`` (``models.obv``) to f32 tolerance —
    the OBV accumulation, first-bar volume normalization, and windowed
    mean follow the generic path's exact op order, and the selection
    contraction is an exact one-hot copy. ``table`` picks the SMA-of-OBV
    table substrate (env ``DBX_OBV_TABLE``; the inline variant carries
    the SMA kernel's division-lowering caveat, `_obv_kernel_inline`).
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    volume = jnp.asarray(volume, jnp.float32)
    window = np.asarray(window)
    T = close.shape[1]
    windows, onehot_w, warm = _obv_grid_setup(
        window.astype(np.float32).tobytes())
    m = _fused_obv_call(close, volume, onehot_w, warm,
                        _t_real_col(t_real, close),
                        windows=windows, T_pad=_round_up(T, 128),
                        W_pad=onehot_w.shape[0], P_real=window.shape[0],
                        T_real=T if t_real is None else None,
                        cost=float(cost), ppy=int(periods_per_year),
                        interpret=bool(interpret),
                        table=_family_table("obv", table),
                        lanes_env=resolve_lanes_cap(),
                        epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "obv_trend",
                           {"close": close, "volume": volume},
                           {"window": window}, t_real=t_real, cost=cost,
                           ppy=periods_per_year, epilogue=epilogue)


@functools.lru_cache(maxsize=4)
def _obv_grid_setup(window_bytes: bytes):
    """Distinct windows + selector and warmup (= window) lanes."""
    window = np.frombuffer(window_bytes, np.float32)
    P = window.shape[0]
    windows = _distinct_windows(window, "windows")
    W_pad = _round_up(max(windows.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    oh = _window_onehot(windows, window, W_pad, P_pad)
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = window
    return (tuple(int(w) for w in windows), _const(oh), _const(warm))


def _trix_kernel(r_ref, ema_ref, oh_ref, asig_ref, warm_ref, *refs,
                 cost: float, ppy: int, T_real: int | None, epilogue: str):
    """TRIX cell: one span-table selection gives the triple-smoothed close;
    the one-bar rate of change is computed in-kernel (a ratio, so the price
    level cancels); the signal line is a per-lane EMA ladder; position =
    sign(trix - signal)."""
    tr, out_ref = _unpack_tr(refs, T_real)
    T_pad = r_ref.shape[1]
    r = r_ref[0]
    dn = (((0,), (0,)), ((), ()))
    e3 = jax.lax.dot_general(ema_ref[0], oh_ref[:], dn,
                             preferred_element_type=jnp.float32,
                             precision=jax.lax.Precision.HIGHEST)
    prev = _shift_down(e3, 1, 1.0)
    # Padded lanes select all-zero table rows (0/0): guard the denominator
    # so they stay finite; real lanes have positive price-level EMAs.
    denom = jnp.where(prev == 0.0, 1.0, prev)
    lanes = oh_ref.shape[1]          # widest legal param block (launcher)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pad, lanes), 0)
    # trix[0] = 0 exactly, matching models.trix (prev seeds with e3[0]).
    trix = jnp.where(t_idx == 0, 0.0, e3 / denom - 1.0)
    a_sig = asig_ref[0, :][None, :]                  # (1, lanes)
    sig = _ema_ladder(trix, a_sig)

    warm = warm_ref[0, :][None, :]                   # 3*span + signal - 2
    valid = t_idx >= (warm.astype(jnp.int32) - 1)
    pos = jnp.where(valid, jnp.sign(trix - sig), 0.0)
    out_ref[0, 0] = _metrics_tail(pos, r, t_idx, tr, cost=cost, ppy=ppy,
                                  epilogue=epilogue)


@functools.partial(
    jax.jit,
    static_argnames=("spans", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_trix_call(close, onehot, a_sig, warm, t_real, *,
                     spans: tuple, T_pad: int, W_pad: int, P_real: int,
                     T_real: int | None, cost: float, ppy: int,
                     interpret: bool, epilogue: str = _EPILOGUE_DEFAULT):
    """Distinct-span triple-EMA table prep + pallas call in one jit."""
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    N = close.shape[0]
    rows = []
    for s in spans:
        a = 2.0 / (float(s) + 1.0)
        rows.append(_ema_rows(_ema_rows(_ema_rows(close_p, a), a), a))
    e3_tbl = jnp.stack(rows, axis=1)                             # (N,W,T_pad)
    if W_pad > len(spans):
        e3_tbl = jnp.concatenate(
            [e3_tbl, jnp.zeros((N, W_pad - len(spans), T_pad),
                               jnp.float32)], axis=1)

    P_pad = a_sig.shape[1]
    # 128 lanes: unlike MACD (+3% at 256), TRIX measured consistently ~4%
    # SLOWER at 256 (14.5-14.8 vs 15.3 M/s) — its ratio + two ladders keep
    # more live state per lane, so the wider block spills what the
    # narrower one keeps resident.
    lanes = _widest_lanes(P_pad, _LANES)
    n_blocks = P_pad // lanes
    kernel = functools.partial(_trix_kernel, cost=cost, ppy=ppy,
                               T_real=T_real, epilogue=epilogue)
    out = pl.pallas_call(
        kernel,
        grid=(N, n_blocks),
        in_specs=[
            pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ] + _tr_specs(T_real),
        out_specs=pl.BlockSpec(
            (1, 1, _METRIC_ROWS, lanes), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (N, n_blocks, _METRIC_ROWS, lanes), jnp.float32),
        interpret=interpret,
    )(_rets3(close_p), e3_tbl, onehot, a_sig, warm,
      *_tr_args(t_real, T_real))
    return Metrics(*(
        jnp.reshape(out[:, :, k, :], (N, P_pad))[:, :P_real]
        for k in range(9)))


def fused_trix_sweep(close, span, signal, *, t_real=None, cost: float = 0.0,
                     periods_per_year: int = 252,
                     interpret: bool | None = None,
                     epilogue: str | None = None,
                     carry_out: bool = False) -> Metrics:
    """Fused TRIX signal-line crossover sweep: ``(N, T)`` x ``(P,)`` lanes.

    ``span``/``signal`` are flat per-combo span arrays (:func:`product_grid`
    order); spans must be integral. Matches ``run_sweep(..., "trix")``
    (``models.trix``) to f32 tolerance — both paths evaluate every EMA with
    the same shift-doubling ladder (``rolling.ema_ladder`` generically,
    ``_ema_rows`` / ``_ema_ladder`` here) and the rate-of-change ratio
    cancels the price level, so the only residual divergence class is the
    MXU selection matmul for the triple-smoothed close.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    span = np.asarray(span)
    signal = np.asarray(signal)
    T = close.shape[1]
    spans, onehot, a_sig, warm = _trix_grid_setup(
        span.astype(np.float32).tobytes(),
        signal.astype(np.float32).tobytes())
    m = _fused_trix_call(close, onehot, a_sig, warm,
                         _t_real_col(t_real, close),
                         spans=spans, T_pad=_round_up(T, 128),
                         W_pad=onehot.shape[0], P_real=span.shape[0],
                         T_real=T if t_real is None else None,
                         cost=float(cost), ppy=int(periods_per_year),
                         interpret=bool(interpret),
                         epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "trix", {"close": close},
                           {"span": span, "signal": signal}, t_real=t_real,
                           cost=cost, ppy=periods_per_year,
                           epilogue=epilogue)


@functools.lru_cache(maxsize=4)
def _trix_grid_setup(span_bytes: bytes, signal_bytes: bytes):
    """Distinct spans + selector, per-lane signal decay and warmup
    (= 3*span + signal - 2, ``models.trix``'s rule)."""
    span = np.frombuffer(span_bytes, np.float32)
    signal = np.frombuffer(signal_bytes, np.float32)
    P = span.shape[0]
    spans = _distinct_windows(span, "spans")
    _distinct_windows(signal, "signal spans")   # validate integrality only
    W_pad = _round_up(max(spans.shape[0], 1), 8)
    P_pad = _round_up(max(P, 1), _LANES)
    oh = _window_onehot(spans, span, W_pad, P_pad)
    a_sig = np.zeros((1, P_pad), np.float32)
    a_sig[0, :P] = 2.0 / (signal + 1.0)
    warm = np.ones((1, P_pad), np.float32)
    warm[0, :P] = 3.0 * span + signal - 2.0
    return (tuple(int(s) for s in spans), _const(oh),
            _const(a_sig), _const(warm))


@functools.partial(
    jax.jit,
    static_argnames=("windows", "T_pad", "W_pad", "P_real", "T_real", "cost",
                     "ppy", "interpret", "epilogue"))
def _fused_vwap_call(close, volume, onehot_w, k_lanes, warm, t_real, *,
                     windows: tuple, T_pad: int, W_pad: int, P_real: int,
                     T_real: int | None, cost: float, ppy: int,
                     interpret: bool, epilogue: str = _EPILOGUE_DEFAULT):
    """VWAP-deviation z-table prep + the *Bollinger* kernel.

    ``models.vwap`` vectorized over the distinct-window axis: rolling VWAP =
    windowed ``sum(close*volume) / sum(volume)`` (two cumsum differences),
    the close's deviation from it z-scored over the same window, fed to the
    shared band machine (enter beyond ±k, exit when price re-crosses the
    anchor: z_exit = 0). The first fused kernel consuming the volume column.

    Replicates the generic float op order on the real-bar region (cumsum-
    difference rolling sums, uncentered rolling-mean numerator, series-
    centered second moments, eps = 1e-12). Warmup rows — where the generic
    path's NaN-filled window sums make ``v > eps`` False and the deviation
    falls back to exactly 0 — are forced to 0 explicitly, as is the
    zero-volume-window fallback.
    """
    T = close.shape[1]
    epilogue = _interp_epilogue(epilogue, T_pad, interpret)
    close_p = _pad_last(close, T_pad)
    vol_p = _pad_last(volume, T_pad)
    w_col, w_f, t_row, windowed_sum, windowed_sum3 = _cumsum_window_tools(
        windows, T_pad)

    pv = windowed_sum(close_p * vol_p)
    v = windowed_sum(vol_p)
    have = (t_row >= (w_col - 1))[None] & (v > _EPS)
    dev = jnp.where(have, close_p[:, None, :] - pv / (v + _EPS), 0.0)

    m = windowed_sum3(dev) / w_f
    # Center with the deviation's mean over the REAL bars (rolling.py's
    # cancellation guard); the pad region never reaches a real output.
    mu = jnp.mean(dev[:, :, :T], axis=2, keepdims=True)
    xc = dev - mu
    s1 = windowed_sum3(xc)
    s2 = windowed_sum3(xc * xc)
    var = jnp.maximum((s2 - s1 * s1 / w_f) / w_f, 0.0)
    z_table = (dev - m) / (jnp.sqrt(var) + _EPS)
    z_table = _pad_w(jnp.where((t_row >= w_col - 1)[None], z_table, 0.0),
                     W_pad)

    kernel = functools.partial(_boll_kernel, cost=cost, ppy=ppy,
                               z_exit=0.0, T_real=T_real, epilogue=epilogue)
    return _band_machine_pallas(
        kernel, close_p, z_table, onehot_w, k_lanes, warm, t_real,
        T_pad=T_pad, W_pad=W_pad, P_real=P_real, T_real=T_real,
        interpret=interpret)


def fused_vwap_sweep(close, volume, window, k, *, t_real=None,
                     cost: float = 0.0, periods_per_year: int = 252,
                     interpret: bool | None = None,
                     epilogue: str | None = None,
                     carry_out: bool = False) -> Metrics:
    """Fused VWAP-deviation reversion sweep: ``(N, T)`` panels x ``(P,)``.

    ``window``/``k`` are flat per-combo arrays (:func:`product_grid` order);
    windows must be integral bar counts. Matches the generic
    ``run_sweep(..., "vwap_reversion")`` path (``models.vwap`` +
    ``signals.band_hysteresis_assoc``): bit-level on CPU interpret mode; on
    TPU the MXU z-selection matmul shares the knife-edge caveat of the other
    band-machine kernels for |z - k| ~ 1e-7 relative.
    """
    _check_carry_out_args(carry_out, t_real)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    close = jnp.asarray(close, jnp.float32)
    volume = jnp.asarray(volume, jnp.float32)
    window = np.asarray(window)
    k = np.asarray(k, np.float32)
    T = close.shape[1]
    P = window.shape[0]

    windows, onehot_w, k_lanes, warm = _vwap_grid_setup(
        window.astype(np.float32).tobytes(), k.tobytes())
    m = _fused_vwap_call(close, volume, onehot_w, k_lanes, warm,
                         _t_real_col(t_real, close),
                         windows=windows,
                         T_pad=_round_up(T, 128), W_pad=onehot_w.shape[0],
                         P_real=P, T_real=T if t_real is None else None,
                         cost=float(cost), ppy=int(periods_per_year),
                         interpret=bool(interpret),
                         epilogue=_resolve_epilogue(epilogue))
    if not carry_out:
        return m
    return _carry_out_tail(m, "vwap_reversion",
                           {"close": close, "volume": volume},
                           {"window": window, "k": k}, t_real=t_real,
                           cost=cost, ppy=periods_per_year,
                           epilogue=epilogue)


@functools.lru_cache(maxsize=4)
def _vwap_grid_setup(window_bytes: bytes, k_bytes: bytes):
    """Like :func:`_boll_grid_setup` but the warmup is ``2*window - 1``:
    the VWAP needs ``window`` bars and its deviation's z-score another
    ``window`` (``models.vwap._positions``'s validity rule)."""
    windows, oh, k_lanes, warm = _boll_grid_setup(window_bytes, k_bytes)
    window = np.frombuffer(window_bytes, np.float32)
    P = window.shape[0]
    warm = np.ones((1, warm.shape[1]), np.float32)
    warm[0, :P] = 2.0 * window - 1.0
    return windows, oh, k_lanes, _const(warm)


# ---------------------------------------------------------------------------
# Ragged paged panel batching (round 10)
#
# A realistic multi-ticker universe holds thousands of symbols with wildly
# different history lengths; dense batching either splits them into
# per-length launch groups or pads every panel to the group max. The paged
# mode stores field data as fixed-size T-pages in a device pool
# (rpc.page_pool.PagePool) and drives the EXISTING fused kernels through a
# per-job page table — the paged-KV discipline of PAPERS.md "Ragged Paged
# Attention" applied to OHLCV:
#
# - `_paged_gather` assembles a group's (n, T_run) field block from the
#   pool with ONE device gather per field (no host restack, no per-panel
#   h2d), then re-imposes the repeat-last padding discipline beyond each
#   ticker's real length — so the assembled block is BIT-IDENTICAL to the
#   dense `_stack_field_ragged` stack and every kernel numerics contract
#   carries over unchanged (including the carry-scan epilogue threading
#   across what are now page boundaries: pad bars earn exactly zero, so
#   the carries freeze at the last real bar regardless of how many pages
#   ride behind it).
# - `fused_paged_sweep` bins the group by PAGE COUNT, so each ticker pads
#   only to its own page boundary — pad work bounded by one page per
#   ticker instead of (t_max - t_i), and a mixed-length group costs one
#   launch per page-count class instead of one per power-of-two length
#   bucket with up-to-2x padding.
#
# The pool side (keying, eviction, upload batching) lives in
# rpc.page_pool; this section owns the kernel-facing schedule and the
# env knobs (`DBX_PAGE_BARS`, `DBX_PAGED`).
# ---------------------------------------------------------------------------

_PAGE_BARS_DEFAULT = 512


def paged_enabled() -> bool:
    """Kill switch for the paged execution path (``DBX_PAGED=0`` routes
    every group through the dense stacks; default on). Read lazily per
    backend construction — never at import time."""
    return os.environ.get("DBX_PAGED", "1") != "0"


def resolve_page_bars() -> int:
    """Validated ``DBX_PAGE_BARS`` page size (default 512 bars).

    Must be a positive multiple of 8 — pages land on the kernels' f32
    sublane tiles, so an off-tile page width would misalign every gather.
    512 balances sharing granularity (an append chain re-uploads at most
    one boundary page) against per-ticker pad waste (< 1 page) and pool
    index overhead; see DESIGN.md "Ragged paged panels".
    """
    raw = os.environ.get("DBX_PAGE_BARS")
    if not raw:
        tuned = _tuned_value("page_bars")
        if tuned is not None:
            try:
                tv = int(tuned)
            except (TypeError, ValueError):
                tv = -1
            if tv >= 8 and tv % 8 == 0:
                return tv
        return _PAGE_BARS_DEFAULT
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"DBX_PAGE_BARS={raw!r} is not an integer (expected a "
            "positive multiple of 8)") from None
    if v < 8 or v % 8:
        raise ValueError(
            f"DBX_PAGE_BARS={v} is unusable: pages must be a positive "
            "multiple of 8 bars (the f32 sublane tile)")
    return v


@functools.partial(jax.jit, static_argnames=("T_run",))
def _paged_gather(pool, table, t_real, *, T_run: int):
    """Assemble an ``(n, T_run)`` field block from the page pool.

    ``pool`` is the ``(slots, page_bars)`` f32 device pool, ``table`` the
    ``(n, max_pages)`` int32 slot table, ``t_real`` the per-ticker real
    bar counts. One gather concatenates each row's pages; the trailing
    select re-imposes the repeat-last padding discipline (bars at
    ``t >= t_real`` replay bar ``t_real - 1``) so the result is
    bit-identical to the dense repeat-last stack no matter what the
    padded table entries point at — table values beyond a ticker's last
    page only need to be in-bounds.
    """
    n = table.shape[0]
    rows = jnp.take(pool, table.reshape(-1), axis=0, mode="clip")
    rows = rows.reshape(n, -1)[:, :T_run]
    t_idx = jax.lax.broadcasted_iota(jnp.int32, (n, T_run), 1)
    tr = t_real.astype(jnp.int32)[:, None]
    last = jnp.take_along_axis(rows, jnp.maximum(tr - 1, 0), axis=1)
    return jnp.where(t_idx < tr, rows, last)


# Paged twin of the worker's fused registry: strategy -> (OHLCV fields the
# kernel consumes, grid axes, wrapper adapter). Every
# rpc.compute._FUSED_STRATEGIES entry MUST have a row here — dbxlint's
# kernel-hygiene rule probes the paged path per registry entry
# (`paged_hygiene_probe`), so a missing row surfaces as a loud finding,
# never as a silently dense-only family.
_PAGED_FAMILIES = {
    "sma_crossover": (
        ("close",), ("fast", "slow"),
        lambda a, g, **kw: fused_sma_sweep(a[0], g["fast"], g["slow"],
                                           **kw)),
    "bollinger": (
        ("close",), ("window", "k"),
        lambda a, g, **kw: fused_bollinger_sweep(a[0], g["window"],
                                                 g["k"], **kw)),
    "bollinger_touch": (
        ("close",), ("window", "k"),
        lambda a, g, **kw: fused_bollinger_touch_sweep(
            a[0], g["window"], g["k"], **kw)),
    "momentum": (
        ("close",), ("lookback",),
        lambda a, g, **kw: fused_momentum_sweep(a[0], g["lookback"], **kw)),
    "donchian": (
        ("close",), ("window",),
        lambda a, g, **kw: fused_donchian_sweep(a[0], g["window"], **kw)),
    "donchian_hl": (
        ("close", "high", "low"), ("window",),
        lambda a, g, **kw: fused_donchian_hl_sweep(
            a[0], a[1], a[2], g["window"], **kw)),
    "rsi": (
        ("close",), ("period", "band"),
        lambda a, g, **kw: fused_rsi_sweep(a[0], g["period"], g["band"],
                                           **kw)),
    "stochastic": (
        ("close", "high", "low"), ("window", "band"),
        lambda a, g, **kw: fused_stochastic_sweep(
            a[0], a[1], a[2], g["window"], g["band"], **kw)),
    "keltner": (
        ("close", "high", "low"), ("window", "k"),
        lambda a, g, **kw: fused_keltner_sweep(
            a[0], a[1], a[2], g["window"], g["k"], **kw)),
    "macd": (
        ("close",), ("fast", "slow", "signal"),
        lambda a, g, **kw: fused_macd_sweep(
            a[0], g["fast"], g["slow"], g["signal"], **kw)),
    "trix": (
        ("close",), ("span", "signal"),
        lambda a, g, **kw: fused_trix_sweep(a[0], g["span"], g["signal"],
                                            **kw)),
    "vwap_reversion": (
        ("close", "volume"), ("window", "k"),
        lambda a, g, **kw: fused_vwap_sweep(
            a[0], a[1], g["window"], g["k"], **kw)),
    "obv_trend": (
        ("close", "volume"), ("window",),
        lambda a, g, **kw: fused_obv_sweep(a[0], a[1], g["window"], **kw)),
}


def paged_supported(strategy: str) -> bool:
    """True when ``strategy`` has a paged execution row."""
    return strategy in _PAGED_FAMILIES


def paged_fields(strategy: str) -> tuple:
    """The OHLCV columns the strategy's paged path gathers."""
    return _PAGED_FAMILIES[strategy][0]


def fused_paged_sweep(strategy: str, pool, tables, t_real, grid, *,
                      cost: float = 0.0, periods_per_year: int = 252,
                      interpret: bool | None = None,
                      epilogue: str | None = None) -> Metrics:
    """Run a (possibly mixed-length) group through the fused kernels from
    the device page pool.

    ``pool`` is the ``(slots, page_bars)`` f32 pool array; ``tables`` maps
    each consumed field to a HOST-side ``(n, max_pages)`` int32 slot
    table (short rows padded with any in-bounds slot — dead under the
    assembly's repeat-last fix); ``t_real`` the per-ticker real lengths;
    ``grid`` the flat per-combo axis arrays (:func:`product_grid` order).

    Schedule: the group is binned by page count, each bin assembled by
    :func:`_paged_gather` at its own max length and swept by the family's
    fused kernel — so a ticker's pad work is bounded by ONE page and a
    heterogeneous fleet costs one launch per page-count class. A bin
    whose lengths are uniform takes the kernels' static-length fast path
    and is bit-identical to the dense fused sweep; ragged bins follow the
    documented repeat-last-pad contract (same bits as the dense ragged
    stack). ``epilogue`` routes the metrics-tail substrate exactly as in
    the dense wrappers — the carry scan threads across page boundaries
    like any other T-block boundary.
    """
    fam = _PAGED_FAMILIES.get(strategy)
    if fam is None:
        raise ValueError(
            f"strategy {strategy!r} has no paged execution row "
            f"(known: {sorted(_PAGED_FAMILIES)})")
    fields, _, call = fam
    missing = [f for f in fields if f not in tables]
    if missing:
        raise ValueError(
            f"paged sweep for {strategy!r} needs page tables for fields "
            f"{list(fields)}; missing {missing}")
    t_real = np.asarray(t_real, np.int32).reshape(-1)
    n = t_real.shape[0]
    if n == 0:
        raise ValueError("paged sweep over an empty group")
    B = int(pool.shape[1])
    pages_of = -(-t_real // B)
    bins: dict = {}
    for i, p in enumerate(pages_of):
        bins.setdefault(int(p), []).append(i)

    kw = dict(cost=float(cost), periods_per_year=int(periods_per_year),
              interpret=interpret, epilogue=epilogue)
    parts = []
    order: list = []
    for p, idx in sorted(bins.items()):
        t_bin = t_real[idx]
        T_bin = int(t_bin.max())
        tr_dev = jnp.asarray(t_bin, jnp.int32)
        arrays = [
            _paged_gather(pool,
                          jnp.asarray(np.asarray(tables[f],
                                                 np.int32)[idx][:, :p]),
                          tr_dev, T_run=T_bin)
            for f in fields]
        uniform = bool((t_bin == T_bin).all())
        parts.append(call(arrays, grid,
                          t_real=None if uniform else t_bin, **kw))
        order.extend(idx)
    if len(parts) == 1:
        return parts[0]
    inv = np.empty(n, np.int64)
    inv[np.asarray(order)] = np.arange(n)
    inv = jnp.asarray(inv)
    return Metrics(*(jnp.concatenate(cols, axis=0)[inv]
                     for cols in zip(*parts)))


# One representative value per grid axis for the tiny hygiene probe —
# the paged twin of analysis.jaxpr_rules._AXIS_VALUES (windows small and
# integral, MACD/TRIX fast < slow, 18 real bars clear every warmup).
_PAGED_PROBE_AXES = {
    "fast": [2.0], "slow": [5.0], "window": [3.0], "k": [1.0],
    "lookback": [2.0], "period": [3.0], "band": [20.0], "signal": [2.0],
    "span": [2.0],
}
_PAGED_PROBE_BARS = (20, 18)    # ragged pair, both 3 pages of 8 bars


def paged_hygiene_probe(strategy: str):
    """``(fn, args)`` tracing the paged path of ``strategy`` over a tiny
    pool + page table — dbxlint's kernel-hygiene rule feeds this to
    ``jax.make_jaxpr`` under both epilogue substrates so the paged
    variants can never silently fall out of lint coverage. Raises for a
    registry entry with no paged row or probe template (the rule reports
    that as a loud finding)."""
    fields, axes, _ = _PAGED_FAMILIES[strategy]
    B = 8
    T = max(_PAGED_PROBE_BARS)
    t_real = np.asarray(_PAGED_PROBE_BARS, np.int32)
    t = np.arange(1, T + 1, dtype=np.float32)
    close = 100.0 + np.sin(t) + 0.01 * t
    by_name = {
        "close": close, "high": close * 1.01, "low": close * 0.99,
        "open": close, "volume": np.full(T, 1e4, np.float32),
    }
    pool_rows: list[np.ndarray] = []
    tables: dict = {}
    n_pages = -(-T // B)
    for f in fields:
        tbl = np.zeros((len(t_real), n_pages), np.int32)
        for i, tr in enumerate(t_real):
            series = (by_name[f][:tr] * (1.0 + 0.001 * i)).astype(
                np.float32)
            pages = [series[s:s + B] for s in range(0, tr, B)]
            pages = [np.concatenate(
                [pg, np.full(B - pg.shape[0], pg[-1], np.float32)])
                if pg.shape[0] < B else pg for pg in pages]
            slots = list(range(len(pool_rows),
                               len(pool_rows) + len(pages)))
            pool_rows.extend(pages)
            tbl[i, :len(slots)] = slots
            tbl[i, len(slots):] = slots[-1]
        tables[f] = tbl
    pool = np.stack(pool_rows)
    grid = {a: np.asarray(_PAGED_PROBE_AXES[a], np.float32) for a in axes}

    def fn(pool_arg):
        return fused_paged_sweep(strategy, pool_arg, tables, t_real, grid,
                                 interpret=True)

    return fn, (pool,)


# ---------------------------------------------------------------------------
# Scenario megakernel (round 18)
#
# A scenario panel is a pure function of (base_digest, params) — the
# scenarios.synth reproducibility contract — so a K-scenario stress sweep
# never needs K panels in HBM: one launch regenerates each panel block by
# block in-trace (the synth generator's per-block threefry schedule,
# fold_in(key, block_index)) and feeds it straight into the family's fused
# sweep. The recompute-from-seed trade of PAPERS.md "Compiler-First State
# Space Duality" layered on the paged mode's block iteration: device bytes
# are O(1) in K (a lax.map carries one scenario's working set at a time;
# only the (T_base,) base panel persists), and the dispatcher ships K
# ~100-byte specs instead of K materialized panels.
#
# Families route through the SAME adapter registry as the paged path
# (_PAGED_FAMILIES) — the generator emits all five OHLCV columns, so every
# fused family is scenario-capable. DBX_SCENARIO_FUSED=0 is the kill
# switch (read host-side, per call).
# ---------------------------------------------------------------------------


def scenario_fused_enabled() -> bool:
    """Kill switch for the fused scenario-sweep path
    (``DBX_SCENARIO_FUSED=0`` keeps every scenario job on the
    dispatcher-materialized ladder rung; default on). Read lazily per
    call — never at import time, and never inside a trace."""
    return os.environ.get("DBX_SCENARIO_FUSED", "1") != "0"


def scenario_supported(strategy: str) -> bool:
    """True when ``strategy`` can serve a spec-batch scenario job (one
    adapter registry with the paged path — the generator emits every
    OHLCV column, so the two capability sets are identical by
    construction)."""
    return strategy in _PAGED_FAMILIES


@functools.lru_cache(maxsize=32)
def _scenario_sweep_fn(strategy: str, grid_items: tuple, n_bars: int,
                       block: int, regimes: int, cost: float, ppy: int,
                       interpret: bool, epilogue: str, _subs: tuple):
    """Build (and cache) the jitted generator x sweep program for one
    static configuration. ``_subs`` pins the family's live substrate
    snapshot (``route_substrates``) into the cache key: the wrappers
    resolve table/lanes knobs at trace time, so an in-process env flip
    must mint a NEW program, not silently reuse a stale compile."""
    from ..scenarios import synth

    fields, _, call = _PAGED_FAMILIES[strategy]
    grid = {k: np.frombuffer(v, np.float32) for k, v in grid_items}

    def run(open_, high, low, close, volume, seed_lo, seed_hi,
            vol_scale, shock):
        def one(xs):
            lo, hi, vs, sh = xs
            key = jax.random.fold_in(jax.random.PRNGKey(lo), hi)
            o, h, l, c, v = synth._gen_impl(
                open_, high, low, close, volume, vs, sh, key,
                n_bars=n_bars, block=block, regimes=regimes)
            by = {"open": o, "high": h, "low": l, "close": c, "volume": v}
            arrays = [by[f][None, :] for f in fields]
            m = call(arrays, grid, t_real=None, cost=cost,
                     periods_per_year=ppy, interpret=interpret,
                     epilogue=epilogue)
            return tuple(x[0] for x in m)

        # lax.map (a scan) holds ONE scenario's generated panel + sweep
        # working set live at a time — the O(1)-in-K device-byte claim.
        ms = jax.lax.map(one, (seed_lo, seed_hi, vol_scale, shock))
        return Metrics(*ms)

    return jax.jit(run)


def fused_scenario_sweep(strategy: str, base, seed_lo, seed_hi,
                         vol_scale, shock, grid, *, n_bars: int,
                         block: int, regimes: int, cost: float = 0.0,
                         periods_per_year: int = 252,
                         interpret: bool | None = None,
                         epilogue: str | None = None) -> Metrics:
    """Run K scenarios of one base panel through a family's fused sweep,
    regenerating each scenario's OHLCV in-trace — the scenario panels
    never exist in HBM.

    ``base`` maps the five OHLCV column names to ``(T_base,)`` arrays of
    the REAL panel; ``seed_lo``/``seed_hi`` are the per-scenario effective
    seed words (:func:`~..scenarios.synth.seed_words` of
    ``scenario_seed(base_digest, params)``) and ``vol_scale``/``shock``
    the per-scenario generator modulation, all ``(K,)``. The
    shape-static generator knobs (``n_bars``/``block``/``regimes``) are
    uniform across the batch — the dispatcher's spec coalescer keys on
    them. Returns :class:`Metrics` with ``(K, P)`` fields, row ``k``
    bit-matching the dense fused sweep over the host-materialized panel
    of spec ``k`` (one shared generator program — cross-pinned by test).
    """
    fam = _PAGED_FAMILIES.get(strategy)
    if fam is None:
        raise ValueError(
            f"strategy {strategy!r} has no scenario execution row "
            f"(known: {sorted(_PAGED_FAMILIES)})")
    if n_bars < 1 or block < 1 or regimes < 1:
        raise ValueError(
            f"scenario sweep needs n_bars/block/regimes >= 1 "
            f"(got {n_bars}/{block}/{regimes})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    grid_items = tuple(sorted(
        (k, np.asarray(v, np.float32).tobytes()) for k, v in grid.items()))
    subs = tuple(sorted(route_substrates(strategy).items()))
    fn = _scenario_sweep_fn(strategy, grid_items, int(n_bars), int(block),
                            int(regimes), float(cost),
                            int(periods_per_year), bool(interpret),
                            _resolve_epilogue(epilogue), subs)
    seed_lo = jnp.asarray(seed_lo, jnp.int32)
    seed_hi = jnp.asarray(seed_hi, jnp.int32)
    if seed_lo.ndim != 1 or seed_lo.shape != seed_hi.shape:
        raise ValueError("seed_lo/seed_hi must be matching (K,) arrays")
    if seed_lo.shape[0] == 0:
        raise ValueError("scenario sweep over an empty spec batch")
    return fn(*(jnp.asarray(np.asarray(base[f]), jnp.float32)
                for f in ("open", "high", "low", "close", "volume")),
              seed_lo, seed_hi,
              jnp.asarray(vol_scale, jnp.float32),
              jnp.asarray(shock, jnp.float32))


# 18 real base bars + 16 generated bars clear every probe axis warmup
# (windows <= 5, MACD/TRIX fast < slow) — the paged probe's sizing rule.
_SCENARIO_PROBE_BARS = 18


def scenario_hygiene_probe(strategy: str):
    """``(fn, args)`` tracing the scenario megakernel path of
    ``strategy`` — the in-trace seed fold, the per-block generator scan
    and the family sweep over the regenerated panel — for dbxlint's
    kernel-hygiene rule (both epilogue substrates, like the paged twin).
    Raises for a family with no scenario row or probe template (the rule
    reports that as a loud finding, never a crashed run)."""
    fields, axes, _ = _PAGED_FAMILIES[strategy]
    del fields
    T = _SCENARIO_PROBE_BARS
    t = np.arange(1, T + 1, dtype=np.float32)
    close = 100.0 + np.sin(t) + 0.01 * t
    base = {
        "open": close, "high": close * 1.01, "low": close * 0.99,
        "close": close, "volume": np.full(T, 1e4, np.float32),
    }
    grid = {a: np.asarray(_PAGED_PROBE_AXES[a], np.float32) for a in axes}
    args = (np.asarray([3, 5], np.int32), np.asarray([1, 2], np.int32),
            np.asarray([2.0, 1.5], np.float32),
            np.asarray([0.1, 0.0], np.float32))

    def fn(lo, hi, vs, sh):
        return fused_scenario_sweep(strategy, base, lo, hi, vs, sh, grid,
                                    n_bars=16, block=4, regimes=2,
                                    interpret=True)

    return fn, args


def scenario_certify_probe():
    """``(fn, args, integral_keys)`` for dbxcert: the fused generator x
    sweep cone on tiny pinned shapes — the flagship family's scenario
    megakernel traced end to end (seed fold -> per-block regeneration ->
    carry-scan sweep -> metrics). The in-sweep regeneration claim is
    sound only if this program is run-to-run deterministic for fixed
    seed words: the certifier asserts no nondet-class primitive reaches
    any metric output, the same machine-checked contract the
    ``scenario_synth`` cone pins for the host/materialized path. The
    two rows TOGETHER are the proof the fused and materialized rungs of
    the degradation ladder cannot silently diverge in kind."""
    from .metrics import Metrics

    probe_fn, args = scenario_hygiene_probe("sma_crossover")

    def fn(lo, hi, vs, sh):
        m = probe_fn(lo, hi, vs, sh)
        return dict(zip(Metrics._fields, m))

    return fn, args, frozenset()
