"""PnL engines: the strategy-signal -> position -> returns state machine.

The reference's compute slot processes a job batch serially with a 1-second
sleep per job (reference ``src/worker/process.rs:21-25``); its intended
replacement is "the strategy-signal/PnL state machine as a single jit+vmap
kernel" (``BASELINE.json`` north_star). Two engines are provided:

- :func:`backtest_prefix` — for **path-free** strategies, where the position at
  bar ``t`` is a pure function of indicators at ``t`` (SMA crossover,
  momentum, band-touch). Pure fused elementwise/cumsum work, no sequential
  dependency: the whole (ticker x param x time) block is one VPU pass. This is
  the fast path that makes millions of backtests/sec possible.
- :func:`backtest_scan` — for **stateful** strategies with hysteresis (hold
  until exit: Bollinger mean-reversion, pairs z-score entry/exit, stops).
  The per-bar state machine runs under ``jax.lax.scan`` with a tiny carry;
  all parameter/ticker lanes advance in lockstep per step, so the scan is
  sequential in T only — exactly the "lax.scan whose carry stays small"
  design called for in SURVEY.md section 7.

Conventions:

- Time is the last axis, shape ``(..., T)``.
- ``positions[t]`` is the target exposure *decided at the close of bar t*; it
  earns ``returns[t+1]``. Transaction cost is charged on ``|delta position|``.
- Warmup bars must carry position 0 (strategies multiply by
  :func:`~..ops.rolling.valid_mask`), never NaN.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array


class BacktestResult(NamedTuple):
    """Per-bar outputs of a backtest, each shaped ``(..., T)``."""

    returns: Array    # net strategy simple returns per bar
    equity: Array     # equity curve, starts at 1.0 implicitly before bar 0
    positions: Array  # target exposure per bar (echo of the input)


def simple_returns(close: Array) -> Array:
    """Per-bar simple returns ``close[t]/close[t-1] - 1``; ``r[0] = 0``."""
    prev = jnp.concatenate([close[..., :1], close[..., :-1]], axis=-1)
    return close / prev - 1.0


def log_returns(close: Array) -> Array:
    """Per-bar log returns; ``r[0] = 0``."""
    prev = jnp.concatenate([close[..., :1], close[..., :-1]], axis=-1)
    return jnp.log(close) - jnp.log(prev)


def _lagged(x: Array) -> Array:
    """``x[t-1]`` with 0 at ``t=0``."""
    return jnp.concatenate([jnp.zeros_like(x[..., :1]), x[..., :-1]], axis=-1)


def backtest_prefix(
    close: Array,
    positions: Array,
    *,
    cost: float | Array = 0.0,
    compound: bool = False,
) -> BacktestResult:
    """Vectorized PnL for path-free position series.

    ``net[t] = positions[t-1] * r[t] - cost * |positions[t] - positions[t-1]|``

    with ``r`` the simple returns of ``close``. Broadcasts: ``close`` may be
    ``(T,)`` or ``(tickers, T)`` while ``positions`` is ``(params, ..., T)``.

    ``compound=False`` (default) gives an additive equity curve ``1 + cumsum``
    — a pure prefix-sum on the VPU; ``compound=True`` compounds via
    ``exp(cumsum(log1p))``.
    """
    r = simple_returns(close)
    prev_pos = _lagged(positions)
    turnover = jnp.abs(positions - prev_pos)
    net = prev_pos * r - jnp.asarray(cost, r.dtype) * turnover
    if compound:
        equity = jnp.exp(jnp.cumsum(jnp.log1p(net), axis=-1))
    else:
        equity = 1.0 + jnp.cumsum(net, axis=-1)
    return BacktestResult(returns=net, equity=equity, positions=positions)


def backtest_scan(
    step: Callable,
    init_carry,
    inputs,
    close: Array,
    *,
    cost: float | Array = 0.0,
    compound: bool = False,
    unroll: int = 8,
) -> BacktestResult:
    """Stateful engine: run ``step`` over bars with ``lax.scan``, then price it.

    ``step(carry, inputs_t) -> (carry, position_t)`` is the per-bar state
    machine. ``inputs`` is a pytree of precomputed indicator arrays with time
    on the **last** axis (they are transposed to scan order here and back);
    indicator math itself stays in the vectorized rolling ops — only the tiny
    hysteresis state lives in the scan carry.

    ``unroll`` trades compile time for fewer loop iterations on TPU.
    """
    xs = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, -1, 0), inputs)
    _, pos_tmajor = jax.lax.scan(step, init_carry, xs, unroll=unroll)
    positions = jnp.moveaxis(pos_tmajor, 0, -1)
    return backtest_prefix(close, positions, cost=cost, compound=compound)
