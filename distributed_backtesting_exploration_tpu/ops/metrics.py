"""Performance metrics over backtest return/equity series.

The reference records only a completion bit per job and ignores the result
payload entirely (reference ``src/server/main.rs:66-78`` — ``CompleteRequest.data``
is never read). Here completions carry real metrics, computed on-device as
fused reductions over the ``(ticker, param)`` grid so that only a few scalars
per backtest ever leave the TPU.

All metrics reduce over the trailing time axis and support an optional
boolean ``mask`` (e.g. to exclude indicator warmup bars) implemented as
weighted reductions — no dynamic shapes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array


# Metrics where a *smaller* value is better; argmax-style selection must
# negate these (see metric_sign). Everything else is higher-is-better.
LOWER_IS_BETTER = frozenset({"max_drawdown", "volatility", "turnover"})


def metric_sign(name: str) -> float:
    """+1.0 for higher-is-better metrics, -1.0 for lower-is-better ones.

    Multiply a metric by its sign before any argmax so that selection code
    (walk-forward refits, cross-chip best_over_grid) optimizes the right
    direction for every :class:`Metrics` field.
    """
    if name not in Metrics._fields:
        raise KeyError(f"unknown metric {name!r}; one of {Metrics._fields}")
    return -1.0 if name in LOWER_IS_BETTER else 1.0


class Metrics(NamedTuple):
    """Scalar (per-series) performance summary; each field is ``(...)``."""

    sharpe: Array
    sortino: Array
    max_drawdown: Array
    total_return: Array
    cagr: Array
    volatility: Array
    hit_rate: Array
    n_trades: Array
    turnover: Array


def _masked_moments(x: Array, mask, ddof: int = 0):
    if mask is None:
        n = jnp.asarray(x.shape[-1], x.dtype)
        s1 = jnp.sum(x, axis=-1)
        s2 = jnp.sum(x * x, axis=-1)
    else:
        m = mask.astype(x.dtype)
        n = jnp.sum(m, axis=-1)
        s1 = jnp.sum(x * m, axis=-1)
        s2 = jnp.sum(x * x * m, axis=-1)
    mean = s1 / jnp.maximum(n, 1.0)
    var = jnp.maximum(s2 / jnp.maximum(n, 1.0) - mean * mean, 0.0)
    if ddof:
        var = var * n / jnp.maximum(n - ddof, 1.0)
    return mean, jnp.sqrt(var), n


def sharpe(returns: Array, *, periods_per_year: int = 252, mask=None,
           eps: float = 1e-12) -> Array:
    """Annualized Sharpe ratio of per-bar returns (risk-free = 0)."""
    mean, std, _ = _masked_moments(returns, mask)
    return mean / (std + eps) * jnp.sqrt(jnp.asarray(periods_per_year, returns.dtype))


def sortino(returns: Array, *, periods_per_year: int = 252, mask=None,
            eps: float = 1e-12) -> Array:
    """Annualized Sortino ratio: mean over downside deviation."""
    m = jnp.ones_like(returns) if mask is None else mask.astype(returns.dtype)
    n = jnp.maximum(jnp.sum(m, axis=-1), 1.0)
    mean = jnp.sum(returns * m, axis=-1) / n
    downside = jnp.minimum(returns, 0.0) * m
    dstd = jnp.sqrt(jnp.sum(downside * downside, axis=-1) / n)
    return mean / (dstd + eps) * jnp.sqrt(jnp.asarray(periods_per_year, returns.dtype))


def max_drawdown(equity: Array) -> Array:
    """Max peak-to-trough drawdown fraction of an equity curve (>= 0)."""
    # Running peak as a shift-doubling ladder, not lax.associative_scan:
    # max is exact under any association order (bit-identical result), the
    # flat pad/slice graph compiles far faster than the scan's recursive
    # lowering, and that lowering's native compile proved load-sensitive
    # on the CPU harness (see signals.prefix_compose_maps).
    from .signals import _shift_last
    peak = equity
    span = 1
    while span < equity.shape[-1]:
        peak = jnp.maximum(peak, _shift_last(peak, span, -jnp.inf))
        span *= 2
    dd = (peak - equity) / jnp.maximum(peak, 1e-12)
    return jnp.max(dd, axis=-1)


def total_return(equity: Array) -> Array:
    """Final equity over the implicit starting equity of 1.0, minus 1."""
    return equity[..., -1] - 1.0


def cagr(equity: Array, *, periods_per_year: int = 252, mask=None) -> Array:
    """Compound annual growth rate implied by the final equity value."""
    T = equity.shape[-1]
    n = jnp.asarray(T, equity.dtype) if mask is None else jnp.sum(
        mask.astype(equity.dtype), axis=-1)
    years = jnp.maximum(n / periods_per_year, 1e-12)
    final = jnp.maximum(equity[..., -1], 1e-12)
    return jnp.power(final, 1.0 / years) - 1.0


def hit_rate(returns: Array, positions: Array, *, mask=None,
             eps: float = 1e-12) -> Array:
    """Fraction of bars with positive net return, among bars with exposure.

    ``mask`` excludes padded bars from the active set — without it, a padded
    batch whose final position is held through the pad counts zero-return
    pad bars in the denominator and dilutes the rate vs the unpadded series.
    """
    active = jnp.abs(_lagged_abs(positions)) > 0
    if mask is not None:
        active = active & mask
    active = active.astype(returns.dtype)
    wins = (returns > 0).astype(returns.dtype) * active
    return jnp.sum(wins, axis=-1) / (jnp.sum(active, axis=-1) + eps)


def _lagged_abs(positions: Array) -> Array:
    return jnp.concatenate(
        [jnp.zeros_like(positions[..., :1]), positions[..., :-1]], axis=-1)


def turnover_total(positions: Array) -> Array:
    """Total absolute position change (round-trip trade = 2.0 for unit size)."""
    prev = _lagged_abs(positions)
    return jnp.sum(jnp.abs(positions - prev), axis=-1)


def n_trades(positions: Array) -> Array:
    """Approximate round-trip trade count: total turnover / 2."""
    return 0.5 * turnover_total(positions)


def metrics_from_reductions(*, s1, s2, downside_sq_sum, mdd, eq_final,
                            wins_sum, active_sum, turnover, n,
                            periods_per_year: int = 252,
                            eps: float = 1e-12) -> Metrics:
    """Assemble a :class:`Metrics` from already-reduced per-series sums.

    The scalar tail of :func:`summary_metrics`, factored out for callers
    whose reductions happen elsewhere — e.g. the time-sharded backtest,
    where ``s1``/``s2``/... arrive from ``psum``/``pmax`` collectives. The
    formulas here are the definitions; distributed callers contribute only
    the reduction topology. (``summary_metrics`` and the fused Pallas
    kernels keep their own evaluation order on purpose — golden tests pin
    their equivalence — because op order is part of their bit-level
    contracts.)
    """
    n = jnp.asarray(n, jnp.float32)
    mean = s1 / n
    std = jnp.sqrt(jnp.maximum(s2 / n - mean * mean, 0.0))
    dstd = jnp.sqrt(downside_sq_sum / n)
    ann = jnp.sqrt(jnp.float32(periods_per_year))
    years = jnp.maximum(n / jnp.float32(periods_per_year), eps)
    final = jnp.maximum(eq_final, eps)
    return Metrics(
        sharpe=mean / (std + eps) * ann,
        sortino=mean / (dstd + eps) * ann,
        max_drawdown=mdd,
        total_return=eq_final - 1.0,
        cagr=jnp.power(final, 1.0 / years) - 1.0,
        volatility=std * ann,
        hit_rate=wins_sum / (active_sum + eps),
        n_trades=0.5 * turnover,
        turnover=turnover,
    )


def summary_metrics(returns: Array, equity: Array, positions: Array, *,
                    periods_per_year: int = 252, mask=None) -> Metrics:
    """All metrics in one fused pass; this is the standard job result payload."""
    return Metrics(
        sharpe=sharpe(returns, periods_per_year=periods_per_year, mask=mask),
        sortino=sortino(returns, periods_per_year=periods_per_year, mask=mask),
        max_drawdown=max_drawdown(equity),
        total_return=total_return(equity),
        cagr=cagr(equity, periods_per_year=periods_per_year, mask=mask),
        volatility=_masked_moments(returns, mask)[1]
        * jnp.sqrt(jnp.asarray(periods_per_year, returns.dtype)),
        hit_rate=hit_rate(returns, positions, mask=mask),
        n_trades=n_trades(positions),
        turnover=turnover_total(positions),
    )
