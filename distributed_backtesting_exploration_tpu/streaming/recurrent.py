"""Scan-form checkpoints and recurrent-form append steps per kernel family.

One :class:`StreamCarry` holds everything needed to advance a finished
T-bar sweep by a ΔT-bar slice without touching the first T bars again:

- **metric accumulators** (``metric``): the shared tail of every fused
  kernel — net-return moment sums (s1/s2/downside), win/active counts,
  turnover, and the carry-scan equity state (cumulative net, running
  peak, max drawdown) threaded exactly like ``ops.fused._equity_scan``
  threads it between T-blocks (``_advance_metrics`` is its recurrent
  form over the LAST axis). Counts and turnover are f32 sums of exact
  small integers, so a (sweep@T + append@ΔT) merge is bit-exact for
  them; moment sums differ from a cold (T+ΔT) sweep only by one f32
  association boundary, and the equity path by the PR-3 block-boundary
  association budget.
- **signal state** (``state`` + ``metric["pos_last"]``): the band/latch
  machines' 3-state position is Markov in the position itself, so the
  last position column IS the compose state; EMA families additionally
  carry their filter values at the last bar (exact state, advanced with
  the textbook recurrence).
- **raw input tail** (``tail``): the last ``tail_bars`` bars of every
  consumed column — enough support that every windowed indicator value
  on appended bars is recomputed from real data with the generic
  models' own op order. While the tail still covers the whole history
  (short panels), the append replays the models verbatim and appended
  positions are bit-identical to the cold sweep; once the tail is
  partial, windowed indicators recompute on the tail window — the same
  values modulo f32 cumsum association, i.e. the knife-edge flip class
  every substrate A/B in this repo budgets (quantified in the parity
  tests).

``build_carry`` (scan form) and ``append_step`` (recurrent form) share
ONE metric-advance implementation, so the two forms cannot drift: the
cold build is literally one advance over the whole panel from the zero
state.

Numerics contract vs the cold sweep at T+ΔT (tested per family):
positions on appended bars bit-identical while the tail covers history
(and modulo the knife-edge class after), turnover/trades/hit counts
bit-exact where positions match, sum metrics within one f32 association
boundary, equity-path metrics within the PR-3 block-association budget.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import io
import json
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import base as models_base
from ..models import donchian as donchian_mod
from ..models import pairs as pairs_mod
from ..models import stochastic as stoch_mod
from ..models import vwap as vwap_mod
from ..ops import fused as fused_ops
from ..ops import pnl as pnl_mod
from ..ops import rolling
from ..ops.metrics import Metrics
from ..utils import data as data_mod

_EPS = 1e-12


# ---------------------------------------------------------------------------
# Carry container + codec
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StreamCarry:
    """Persistable checkpoint of a (panel, strategy, param-block) sweep
    after ``n_bars`` bars. Array leaves are jax arrays (device-resident
    when cached at the device level); ``carry_to_bytes`` round-trips the
    whole thing losslessly for the host level / the wire."""

    strategy: str
    grid: dict                      # flat per-combo (P,) float32 axes
    cost: float
    ppy: int
    n_bars: int
    tail: dict                      # field -> (N, K) f32 raw input tail
    state: dict                     # family signal state (EMA values, ...)
    metric: dict                    # shared metric accumulators, (N, P) f32

    @property
    def nbytes(self) -> int:
        return int(sum(int(np.asarray(a).nbytes)
                       for d in (self.grid, self.tail, self.state,
                                 self.metric)
                       for a in d.values()))


def stream_key(strategy: str, grid, cost: float, ppy: int) -> str:
    """Content key of the carry's parameter block: the digest that —
    together with the panel digest — addresses a checkpoint. Canonical
    over axis order (sorted names) and array bytes."""
    h = hashlib.blake2b(digest_size=16)
    h.update(strategy.encode())
    for name in sorted(grid):
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(grid[name],
                                                 np.float32)).tobytes())
    h.update(np.float32(cost).tobytes())
    h.update(str(int(ppy)).encode())
    return h.hexdigest()


def carry_to_bytes(carry: StreamCarry) -> bytes:
    """Serialize a checkpoint (npz + JSON meta). Lossless: restoring and
    appending bit-matches appending to the never-serialized carry."""
    arrays = {}
    for ns, d in (("g", carry.grid), ("t", carry.tail),
                  ("s", carry.state), ("m", carry.metric)):
        for k, v in d.items():
            arrays[f"{ns}/{k}"] = np.asarray(v)
    meta = json.dumps({"strategy": carry.strategy, "cost": carry.cost,
                       "ppy": carry.ppy, "n_bars": carry.n_bars})
    buf = io.BytesIO()
    np.savez(buf, **{"meta": np.asarray(meta)}, **arrays)
    return buf.getvalue()


def carry_from_bytes(data: bytes) -> StreamCarry:
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(str(z["meta"]))
        out = {"g": {}, "t": {}, "s": {}, "m": {}}
        for key in z.files:
            if key == "meta":
                continue
            ns, _, name = key.partition("/")
            out[ns][name] = jnp.asarray(z[key])
    return StreamCarry(strategy=meta["strategy"], grid=out["g"],
                      cost=float(meta["cost"]), ppy=int(meta["ppy"]),
                      n_bars=int(meta["n_bars"]), tail=out["t"],
                      state=out["s"], metric=out["m"])


# ---------------------------------------------------------------------------
# Shared metric accumulators (the recurrent form of the kernels' tail)
# ---------------------------------------------------------------------------

def _metric_init(n: int, p: int) -> dict:
    z = jnp.zeros((n, p), jnp.float32)
    return {"s1": z, "s2": z, "dsum": z, "wins": z, "active": z,
            "turnover": z, "pos_last": z, "cum": z,
            "peak": jnp.full((n, p), -jnp.inf, jnp.float32), "mdd": z}


# The equity-state step is fused.py's: the scan form (`_equity_scan`)
# and this recurrent form live next to each other so the carry threading
# cannot drift between the substrates.
_equity_advance = fused_ops._equity_advance


def _advance_metrics(metric: dict, pos, ret, *, cost: float,
                     block: int) -> dict:
    """Fold a ``(N, P, D)`` position slice (and its ``(N, 1|P, D)``
    returns) into the accumulators. The scan form (build) calls this once
    with D = T from the zero state; the recurrent form calls it with
    D = ΔT from the stored state — one implementation, no drift."""
    # Anchor dtypes: a position path built purely from Python-scalar
    # selects (the band-touch machine) is WEAKLY typed f32 — letting it
    # into the carry would make downstream dtype depend on a constant's
    # Python type (kernel-hygiene's weak-type rule caught exactly this).
    pos = jnp.asarray(pos, jnp.float32)
    ret = jnp.asarray(ret, jnp.float32)
    prev = jnp.concatenate([metric["pos_last"][..., None], pos[..., :-1]],
                           axis=-1)
    dpos = jnp.abs(pos - prev)
    net = prev * ret - jnp.float32(cost) * dpos
    down = jnp.minimum(net, 0.0)
    active = jnp.abs(prev) > 0
    wins = (net > 0) & active
    cum, peak, mdd = _equity_advance(net, block, metric["cum"],
                                     metric["peak"], metric["mdd"])
    return {
        "s1": metric["s1"] + jnp.sum(net, axis=-1),
        "s2": metric["s2"] + jnp.sum(net * net, axis=-1),
        "dsum": metric["dsum"] + jnp.sum(down * down, axis=-1),
        "wins": metric["wins"] + jnp.sum(wins.astype(jnp.float32), axis=-1),
        "active": metric["active"] + jnp.sum(active.astype(jnp.float32),
                                             axis=-1),
        "turnover": metric["turnover"] + jnp.sum(dpos, axis=-1),
        "pos_last": pos[..., -1],
        "cum": cum, "peak": peak, "mdd": mdd,
    }


def _finalize_impl(metric: dict, n, *, ppy: int) -> Metrics:
    """Accumulators -> the 9 metrics, replicating
    ``ops.fused._metrics_pack``'s final op order. Kept un-jitted beside
    its jitted wrapper so dbxcert (analysis.certify) re-traces the LIVE
    module code — tracing through the jit wrapper would serve a stale
    cached jaxpr and hide the very edits the contract gate exists to
    catch."""
    n = jnp.float32(n)
    mean = metric["s1"] / n
    var = jnp.maximum(metric["s2"] / n - mean * mean, 0.0)
    std = jnp.sqrt(var)
    ann = jnp.sqrt(jnp.float32(ppy))
    dstd = jnp.sqrt(metric["dsum"] / n)
    hit = metric["wins"] / (metric["active"] + _EPS)
    years = jnp.maximum(n / jnp.float32(ppy), _EPS)
    eq_final = 1.0 + metric["cum"]
    final = jnp.maximum(eq_final, _EPS)
    return Metrics(
        sharpe=mean / (std + _EPS) * ann,
        sortino=mean / (dstd + _EPS) * ann,
        max_drawdown=metric["mdd"],
        total_return=eq_final - 1.0,
        cagr=jnp.power(final, 1.0 / years) - 1.0,
        volatility=std * ann,
        hit_rate=hit,
        n_trades=0.5 * metric["turnover"],
        turnover=metric["turnover"],
    )


_finalize_jit = functools.partial(jax.jit, static_argnames=("ppy",))(
    _finalize_impl)


def finalize(carry: StreamCarry) -> Metrics:
    """The checkpoint's 9 metrics over its whole history, ``(N, P)``."""
    return _finalize_jit(carry.metric, np.float32(carry.n_bars),
                         ppy=carry.ppy)


# ---------------------------------------------------------------------------
# Family registry: tail sizing + partial-tail signal heads
# ---------------------------------------------------------------------------

def _mw(grid, *names) -> int:
    return int(max(int(round(float(np.max(np.asarray(grid[n])))))
                   for n in names))


class _StreamSpec(NamedTuple):
    """One streaming family row: consumed columns, tail sizing, and the
    partial-tail head (None = window replay through the generic model —
    valid for memoryless families whose indicators are shift/scale
    invariant over the tail window)."""

    fields: tuple
    tail_bars: Callable             # grid -> int
    head: Callable | None = None    # (win, D, grid, state, pos0) ->
                                    #   (pos_delta, ret_delta|None, state')


def _band_advance(z, z_entry, z_exit, pos0):
    """Recurrent form of ``ops.signals.band_hysteresis``: advance the
    3-state machine over a ``(N, P, D)`` z slice from the carried
    position. Selection-only (no float arithmetic on the state), so the
    advanced path is bit-identical to the cold machine given the same z."""
    def step(pos, z_t):
        entered = jnp.where(z_t < -z_entry, 1.0,
                            jnp.where(z_t > z_entry, -1.0, 0.0))
        exit_long = (pos > 0) & (z_t >= -z_exit)
        exit_short = (pos < 0) & (z_t <= z_exit)
        held = jnp.where(exit_long | exit_short, 0.0, pos)
        nxt = jnp.where(pos == 0, entered, held)
        return nxt, nxt

    _, pos_t = jax.lax.scan(step, pos0, jnp.moveaxis(z, -1, 0))
    return jnp.moveaxis(pos_t, 0, -1)


def _latch_advance(up, down, pos0):
    """Recurrent form of ``models.donchian._latch`` (valid region only)."""
    def step(pos, inp):
        up_t, down_t = inp
        nxt = jnp.where(up_t, 1.0, jnp.where(down_t, -1.0, pos))
        return nxt, nxt

    xs = (jnp.moveaxis(up, -1, 0), jnp.moveaxis(down, -1, 0))
    _, pos_t = jax.lax.scan(step, pos0, xs)
    return jnp.moveaxis(pos_t, 0, -1)


def _per_lane(fn, rows, grid):
    """vmap ``fn(*single_rows, params)`` over tickers (axis 0) and the
    flat param grid — the same (ticker x param) fan-out the generic sweep
    uses, so indicator op order matches the semantics-defining path."""
    def per_ticker(*r):
        return jax.vmap(lambda p: fn(*r, p))(dict(grid))
    return jax.vmap(per_ticker)(*rows)


def _ohlcv_rows(rows: dict):
    close = rows["close"]
    return data_mod.OHLCV(
        open=rows.get("open", close), high=rows.get("high", close),
        low=rows.get("low", close), close=close,
        volume=rows.get("volume", jnp.ones_like(close)))


def _positions_full(strategy: str, fields: dict, grid):
    """Positions over a full-history window via the generic models —
    ``(N, P, T)`` (pairs also returns beta). THE semantics-defining path:
    whatever it computes is what the cold sweep means."""
    if strategy == "pairs":
        return _per_lane(lambda y, x, p: pairs_mod.pairs_positions(y, x, p),
                         [fields["close"], fields["close2"]], grid)
    strat = models_base.get_strategy(strategy)
    names = [f for f in data_mod.OHLCV._fields if f in fields]

    def fn(*rows, _names=tuple(names)):
        *cols, params = rows
        o = _ohlcv_rows(dict(zip(_names, cols)))
        return strat.positions(o, params)

    return _per_lane(lambda *r: fn(*r), [fields[f] for f in names], grid)


def _pairs_hedged_returns(y, x, beta):
    """``models.pairs.pair_net_returns``'s hedged-return op order."""
    ry = pnl_mod.simple_returns(y)[:, None, :]
    rx = pnl_mod.simple_returns(x)[:, None, :]
    prev_beta = jnp.concatenate(
        [jnp.zeros_like(beta[..., :1]), beta[..., :-1]], axis=-1)
    gross = 1.0 + jnp.abs(prev_beta)
    return (ry - prev_beta * rx) / jnp.maximum(gross, 1.0)


def _extract_state(strategy: str, fields: dict, grid) -> dict:
    """Exact signal state at the window's last bar, from the models' own
    filters (EMA families; everything else is stateless beyond the tail
    + the metric state's last position)."""
    close = fields["close"]
    if strategy == "rsi":
        diff = jnp.diff(close, axis=-1, prepend=close[..., :1])
        gains, losses = jnp.maximum(diff, 0.0), jnp.maximum(-diff, 0.0)
        ag = _per_lane(lambda g, p: rolling.ema(g, alpha=1.0 / p["period"]),
                       [gains], grid)[..., -1]
        al = _per_lane(lambda l, p: rolling.ema(l, alpha=1.0 / p["period"]),
                       [losses], grid)[..., -1]
        return {"ag": ag, "al": al}
    if strategy == "macd":
        def fn(c, p):
            x = c - c[:1]
            ef = rolling.ema_ladder(x, span=p["fast"])
            es = rolling.ema_ladder(x, span=p["slow"])
            esig = rolling.ema_ladder(ef - es, span=p["signal"])
            return ef[-1], es[-1], esig[-1]
        ef, es, esig = _per_lane(fn, [close], grid)
        return {"ef": ef, "es": es, "esig": esig,
                "c0": close[..., :1]}
    if strategy == "trix":
        def fn(c, p):
            e1 = rolling.ema_ladder(c, span=p["span"])
            e2 = rolling.ema_ladder(e1, span=p["span"])
            e3 = rolling.ema_ladder(e2, span=p["span"])
            prev = jnp.concatenate([e3[:1], e3[:-1]], axis=-1)
            trix = e3 / prev - 1.0
            esig = rolling.ema_ladder(trix, span=p["signal"])
            return e1[-1], e2[-1], e3[-1], esig[-1]
        e1, e2, e3, esig = _per_lane(fn, [close], grid)
        return {"e1": e1, "e2": e2, "e3": e3, "esig": esig}
    if strategy == "keltner":
        mid = _per_lane(lambda c, p: rolling.ema(c, span=p["window"]),
                        [close], grid)[..., -1]
        return {"mid": mid}
    return {}


# -- partial-tail heads ------------------------------------------------------
# Every head runs with n_bars > tail_bars(grid) >= max warmup, so every
# delta bar is past warmup for every lane — no validity masks needed.

def _head_bollinger(win, D, grid, state, pos0):
    K = win["close"].shape[-1] - D
    z = _per_lane(lambda c, p: rolling.rolling_zscore(c, p["window"],
                                                      fill=0.0),
                  [win["close"]], grid)[..., K:]
    return _band_advance(z, grid["k"], 0.0, pos0), None, state


def _head_stochastic(win, D, grid, state, pos0):
    K = win["close"].shape[-1] - D
    z = _per_lane(
        lambda h, l, c, p: stoch_mod.stochastic_k(h, l, c, p["window"]),
        [win["high"], win["low"], win["close"]], grid)[..., K:] - 50.0
    return _band_advance(z, grid["band"], 0.0, pos0), None, state


def _head_vwap(win, D, grid, state, pos0):
    K = win["close"].shape[-1] - D

    def fn(c, v, p):
        dev = c - vwap_mod.rolling_vwap(c, v, p["window"])
        return rolling.rolling_zscore(dev, p["window"], fill=0.0)

    z = _per_lane(fn, [win["close"], win["volume"]], grid)[..., K:]
    return _band_advance(z, grid["k"], 0.0, pos0), None, state


def _head_keltner(win, D, grid, state, pos0):
    close = win["close"]
    K = close.shape[-1] - D
    a = 2.0 / (grid["window"] + 1.0)                         # (P,)

    def step(mid, c_t):                                      # c_t (N, 1)
        mid = (1.0 - a) * mid + a * c_t
        return mid, mid

    xs = jnp.moveaxis(close[..., K:], -1, 0)[..., None]      # (D, N, 1)
    mid_last, mids = jax.lax.scan(step, state["mid"], xs)
    mids = jnp.moveaxis(mids, 0, -1)                         # (N, P, D)
    atr = _per_lane(
        lambda h, l, c, p: rolling.rolling_mean(
            keltner_true_range(h, l, c), p["window"], fill=jnp.nan),
        [win["high"], win["low"], close], grid)[..., K:]
    dev = close[:, None, K:] - mids
    z = jnp.where(atr > _EPS, dev / (atr + _EPS), 0.0)
    return (_band_advance(z, grid["k"], 0.0, pos0), None,
            {"mid": mid_last})


def keltner_true_range(high, low, close):
    from ..models import keltner as keltner_mod
    return keltner_mod.true_range(high, low, close)


def _head_rsi(win, D, grid, state, pos0):
    close = win["close"]
    K = close.shape[-1] - D
    a = 1.0 / grid["period"]                                 # (P,)

    def step(carry, c_t):                                    # c_t (N, 1)
        ag, al, pc = carry
        diff = c_t - pc
        ag = (1.0 - a) * ag + a * jnp.maximum(diff, 0.0)
        al = (1.0 - a) * al + a * jnp.maximum(-diff, 0.0)
        rsi = 100.0 - 100.0 / (1.0 + ag / (al + _EPS))
        return (ag, al, c_t), rsi - 50.0

    xs = jnp.moveaxis(close[..., K:], -1, 0)[..., None]
    (ag, al, _), z = jax.lax.scan(
        step, (state["ag"], state["al"], close[..., K - 1:K]), xs)
    z = jnp.moveaxis(z, 0, -1)
    return (_band_advance(z, grid["band"], 0.0, pos0), None,
            {"ag": ag, "al": al})


def _head_macd(win, D, grid, state, pos0):
    close = win["close"]
    K = close.shape[-1] - D
    af = 2.0 / (grid["fast"] + 1.0)
    as_ = 2.0 / (grid["slow"] + 1.0)
    ag = 2.0 / (grid["signal"] + 1.0)
    c0 = state["c0"]

    def step(carry, c_t):
        ef, es, esig = carry
        x = c_t - c0
        ef = (1.0 - af) * ef + af * x
        es = (1.0 - as_) * es + as_ * x
        macd = ef - es
        esig = (1.0 - ag) * esig + ag * macd
        return (ef, es, esig), jnp.sign(macd - esig)

    xs = jnp.moveaxis(close[..., K:], -1, 0)[..., None]
    (ef, es, esig), pos = jax.lax.scan(
        step, (state["ef"], state["es"], state["esig"]), xs)
    return (jnp.moveaxis(pos, 0, -1), None,
            {"ef": ef, "es": es, "esig": esig, "c0": c0})


def _head_trix(win, D, grid, state, pos0):
    close = win["close"]
    K = close.shape[-1] - D
    a = 2.0 / (grid["span"] + 1.0)
    ag = 2.0 / (grid["signal"] + 1.0)

    def step(carry, c_t):
        e1, e2, e3, esig = carry
        e1 = (1.0 - a) * e1 + a * c_t
        e2 = (1.0 - a) * e2 + a * e1
        e3n = (1.0 - a) * e3 + a * e2
        trix = e3n / e3 - 1.0
        esig = (1.0 - ag) * esig + ag * trix
        return (e1, e2, e3n, esig), jnp.sign(trix - esig)

    xs = jnp.moveaxis(close[..., K:], -1, 0)[..., None]
    (e1, e2, e3, esig), pos = jax.lax.scan(
        step, (state["e1"], state["e2"], state["e3"], state["esig"]), xs)
    return (jnp.moveaxis(pos, 0, -1), None,
            {"e1": e1, "e2": e2, "e3": e3, "esig": esig})


def _donchian_head(hi_src: str, lo_src: str):
    def head(win, D, grid, state, pos0):
        close = win["close"]
        K = close.shape[-1] - D
        hi = _per_lane(
            lambda s, p: rolling.rolling_extrema_traced(
                s, p["window"], max_window=donchian_mod.MAX_WINDOW,
                mode="max", fill=jnp.inf),
            [win[hi_src]], grid)
        lo = _per_lane(
            lambda s, p: rolling.rolling_extrema_traced(
                s, p["window"], max_window=donchian_mod.MAX_WINDOW,
                mode="min", fill=-jnp.inf),
            [win[lo_src]], grid)
        hi_prev = jnp.concatenate(
            [jnp.full_like(hi[..., :1], jnp.inf), hi[..., :-1]], axis=-1)
        lo_prev = jnp.concatenate(
            [jnp.full_like(lo[..., :1], -jnp.inf), lo[..., :-1]], axis=-1)
        c3 = close[:, None, :]
        up = (c3 >= hi_prev)[..., K:]
        down = (c3 <= lo_prev)[..., K:]
        return _latch_advance(up, down, pos0), None, state
    return head


def _head_pairs(win, D, grid, state, pos0):
    y, x = win["close"], win["close2"]
    K = y.shape[-1] - D
    beta, z, _ = _per_lane(
        lambda yy, xx, p: pairs_mod.pair_signals(yy, xx, p["lookback"]),
        [y, x], grid)
    pos = _band_advance(z[..., K:], grid["z_entry"],
                        grid.get("z_exit", 0.0), pos0)
    hr = _pairs_hedged_returns(y, x, beta)[..., K:]
    return pos, hr, state


_STREAM_FAMILIES = {
    "sma_crossover": _StreamSpec(
        ("close",), lambda g: _mw(g, "fast", "slow") + 2),
    "momentum": _StreamSpec(("close",), lambda g: _mw(g, "lookback") + 2),
    "bollinger_touch": _StreamSpec(("close",),
                                   lambda g: _mw(g, "window") + 2),
    "obv_trend": _StreamSpec(("close", "volume"),
                             lambda g: _mw(g, "window") + 2),
    "bollinger": _StreamSpec(("close",), lambda g: _mw(g, "window") + 2,
                             _head_bollinger),
    "stochastic": _StreamSpec(("close", "high", "low"),
                              lambda g: _mw(g, "window") + 2,
                              _head_stochastic),
    "vwap_reversion": _StreamSpec(("close", "volume"),
                                  lambda g: 2 * _mw(g, "window") + 2,
                                  _head_vwap),
    "keltner": _StreamSpec(("close", "high", "low"),
                           lambda g: _mw(g, "window") + 2, _head_keltner),
    "rsi": _StreamSpec(("close",), lambda g: _mw(g, "period") + 2,
                       _head_rsi),
    "macd": _StreamSpec(
        ("close",), lambda g: _mw(g, "slow") + _mw(g, "signal") + 2,
        _head_macd),
    "trix": _StreamSpec(
        ("close",), lambda g: 3 * _mw(g, "span") + _mw(g, "signal") + 2,
        _head_trix),
    "donchian": _StreamSpec(("close",), lambda g: _mw(g, "window") + 3,
                            _donchian_head("close", "close")),
    "donchian_hl": _StreamSpec(("close", "high", "low"),
                               lambda g: _mw(g, "window") + 3,
                               _donchian_head("high", "low")),
    "pairs": _StreamSpec(("close", "close2"),
                         lambda g: 2 * _mw(g, "lookback") + 2, _head_pairs),
}


def supports_strategy(strategy: str) -> bool:
    return strategy in _STREAM_FAMILIES


def stream_fields(strategy: str) -> tuple:
    """OHLCV columns the family's signal head consumes (``close2`` = the
    pairs x leg)."""
    return _STREAM_FAMILIES[strategy].fields


def tail_bars(strategy: str, grid) -> int:
    """Raw-input bars the carry retains: every windowed indicator (and
    its warmup chain) on an appended bar is recomputable from this many
    trailing bars."""
    return _STREAM_FAMILIES[strategy].tail_bars(grid)


# ---------------------------------------------------------------------------
# Scan form (build) + recurrent form (append)
# ---------------------------------------------------------------------------

def _grid_jnp(grid) -> dict:
    return {k: jnp.asarray(np.asarray(v, np.float32).reshape(-1))
            for k, v in grid.items()}


def _single_asset_ret(close):
    return pnl_mod.simple_returns(close)[:, None, :]


def _build_impl(fields, grid, *, strategy: str, cost: float, block: int):
    out = _positions_full(strategy, fields, grid)
    if strategy == "pairs":
        pos, beta = out
        ret = _pairs_hedged_returns(fields["close"], fields["close2"], beta)
    else:
        pos, ret = out, _single_asset_ret(fields["close"])
    n, p = pos.shape[0], pos.shape[1]
    metric = _advance_metrics(_metric_init(n, p), pos, ret, cost=cost,
                              block=block)
    return metric, _extract_state(strategy, fields, grid)


# Scan form, jitted for serving (the un-jitted body is the certify trace
# target — see _finalize_impl's rationale).
_build_jit = functools.partial(
    jax.jit, static_argnames=("strategy", "cost", "block"))(_build_impl)


def _append_impl(tail, delta, grid, state, metric, *, strategy: str,
                 cost: float, block: int, D: int, full_cover: bool,
                 K_new: int):
    win = {f: jnp.concatenate([tail[f], delta[f]], axis=-1) for f in tail}
    K = win["close"].shape[-1] - D
    spec = _STREAM_FAMILIES[strategy]
    if full_cover or spec.head is None:
        out = _positions_full(strategy, win, grid)
        if strategy == "pairs":
            pos_w, beta = out
            ret_d = _pairs_hedged_returns(win["close"], win["close2"],
                                          beta)[..., K:]
        else:
            pos_w, ret_d = out, None
        pos_d = pos_w[..., K:]
        state = _extract_state(strategy, win, grid) if full_cover else state
    else:
        pos_d, ret_d, state = spec.head(win, D, grid, state,
                                        metric["pos_last"])
    if ret_d is None:
        ret_d = _single_asset_ret(win["close"])[..., K:]
    metric = _advance_metrics(metric, pos_d, ret_d, cost=cost, block=block)
    new_tail = {f: win[f][..., -K_new:] for f in win}
    return new_tail, state, metric


# Recurrent form, jitted for serving.
_append_jit = functools.partial(
    jax.jit, static_argnames=("strategy", "cost", "block", "D",
                              "full_cover", "K_new"))(_append_impl)


# Host-side unroll bound for the blocked equity advance: each block
# emits its own prefix ops, and XLA-CPU's compile wall grows with the
# emitted block count far faster than Mosaic's (the kernels keep 256).
# Looser blocks only move f32 association inside the PR-3 budget.
_HOST_MAX_BLOCKS = 32


def _block(n: int, epilogue: str | None) -> int:
    n = max(n, 1)
    epi = fused_ops._resolve_epilogue(epilogue)
    if epi == "ladder":
        return n                   # one block: the full-length scan
    b = fused_ops._scan_block(n, epi)
    while -(-n // b) > _HOST_MAX_BLOCKS:
        b *= 2
    return b


def _np_fields(fields: dict) -> dict:
    return {k: jnp.asarray(np.asarray(v, np.float32)) for k, v in
            fields.items()}


def build_carry(strategy: str, fields: dict, grid, *, cost: float = 0.0,
                periods_per_year: int = 252,
                epilogue: str | None = None) -> StreamCarry:
    """Scan form: run the full ``(N, T)`` panel once, return the
    checkpoint. ``fields`` maps consumed column names (``close`` [+
    ``high``/``low``/``volume``; ``close2`` for pairs]) to ``(N, T)``
    arrays; ``grid`` is the flat per-combo axes dict (product order)."""
    if strategy not in _STREAM_FAMILIES:
        raise ValueError(f"strategy {strategy!r} has no streaming family; "
                         f"known: {sorted(_STREAM_FAMILIES)}")
    spec = _STREAM_FAMILIES[strategy]
    missing = [f for f in spec.fields if f not in fields]
    if missing:
        raise ValueError(f"streaming {strategy} needs fields {missing}")
    fields = {f: v for f, v in _np_fields(fields).items()
              if f in spec.fields}
    grid_np = {k: np.asarray(v, np.float32).reshape(-1)
               for k, v in grid.items()}
    gj = _grid_jnp(grid_np)
    T = int(fields["close"].shape[-1])
    metric, state = _build_jit(fields, gj, strategy=strategy,
                               cost=float(cost),
                               block=_block(T, epilogue))
    K = min(T, tail_bars(strategy, grid_np))
    tail = {f: v[..., -K:] for f, v in fields.items()}
    return StreamCarry(strategy=strategy, grid=grid_np, cost=float(cost),
                      ppy=int(periods_per_year), n_bars=T, tail=tail,
                      state=state, metric=metric)


def append_step(carry: StreamCarry, delta_fields: dict, *,
                epilogue: str | None = None) -> StreamCarry:
    """Recurrent form (the ``_append_step`` of each registered family):
    advance a checkpoint by a ``(N, D)`` bar slice in O(D) work. Returns
    a NEW carry (the input is not mutated — retried jobs can re-advance
    the stored base safely)."""
    spec = _STREAM_FAMILIES[carry.strategy]
    delta = {f: v for f, v in _np_fields(delta_fields).items()
             if f in spec.fields}
    missing = [f for f in spec.fields if f not in delta]
    if missing:
        raise ValueError(
            f"append for {carry.strategy} needs delta fields {missing}")
    D = int(delta["close"].shape[-1])
    if D < 1:
        raise ValueError("empty delta slice")
    K = int(carry.tail["close"].shape[-1])
    tb = tail_bars(carry.strategy, carry.grid)
    full_cover = carry.n_bars == K      # tail still holds ALL history
    n_new = carry.n_bars + D
    K_new = min(n_new, tb)
    tail, state, metric = _append_jit(
        carry.tail, delta, _grid_jnp(carry.grid), carry.state,
        carry.metric, strategy=carry.strategy, cost=carry.cost,
        block=_block(D, epilogue), D=D, full_cover=full_cover,
        K_new=K_new)
    return StreamCarry(strategy=carry.strategy, grid=carry.grid,
                      cost=carry.cost, ppy=carry.ppy, n_bars=n_new,
                      tail=tail, state=state, metric=metric)


# Alias matching the kernel-registry naming in the design docs: the
# recurrent entry the lint layer traces per family.
_append_step = append_step


_PROBE_DELTA_BARS = 4


@functools.lru_cache(maxsize=None)
def _probe_inputs(strategy: str):
    """Tiny concrete (carry, delta, grid) for kernel-hygiene tracing —
    cached per family (the build compiles once; the trace itself is
    re-run per epilogue substrate and never compiles)."""
    spec = _STREAM_FAMILIES[strategy]
    axes = {"fast": [2.0], "slow": [5.0], "window": [3.0], "k": [1.0],
            "lookback": [3.0], "period": [3.0], "band": [20.0],
            "signal": [2.0], "span": [2.0], "z_entry": [1.0],
            "z_exit": [0.0]}
    strat_axes = {
        "sma_crossover": ("fast", "slow"), "momentum": ("lookback",),
        "bollinger": ("window", "k"), "bollinger_touch": ("window", "k"),
        "obv_trend": ("window",), "stochastic": ("window", "band"),
        "vwap_reversion": ("window", "k"), "keltner": ("window", "k"),
        "rsi": ("period", "band"), "macd": ("fast", "slow", "signal"),
        "trix": ("span", "signal"), "donchian": ("window",),
        "donchian_hl": ("window",), "pairs": ("lookback", "z_entry",
                                              "z_exit"),
    }[strategy]
    grid = {a: np.asarray(axes[a], np.float32) for a in strat_axes}
    rng = np.random.default_rng(7)
    T, D = tail_bars(strategy, grid) + 6, _PROBE_DELTA_BARS

    def series():
        walk = np.cumsum(rng.standard_normal(T + D) * 0.5)
        return (100.0 + walk).astype(np.float32)[None, :]

    close = series()
    fields = {}
    for f in spec.fields:
        fields[f] = {"close": close, "high": close * 1.01,
                     "low": close * 0.99,
                     "volume": np.full_like(close, 1e4),
                     "close2": series() * 0.9}[f]
    base = {f: v[..., :T] for f, v in fields.items()}
    carry = build_carry(strategy, base, grid)
    delta = {f: np.asarray(v[..., T:]) for f, v in fields.items()}
    return carry, delta, grid, base


def hygiene_probe(strategy: str):
    """``(fn, args)`` for dbxlint kernel-hygiene: ``fn(*args)`` traces one
    recurrent append step (partial-tail signal head + metric advance +
    finalize) over tiny concrete inputs. The block schedule resolves the
    active ``DBX_EPILOGUE`` at call time, so the rule's substrate sweep
    traces both epilogues like the fused kernels'."""
    carry, delta, grid, _ = _probe_inputs(strategy)
    D = _PROBE_DELTA_BARS
    epi_block = _block(D, None)
    K_new = int(carry.tail["close"].shape[-1])

    def fn(tail, delta_a, state, metric):
        new_tail, new_state, new_metric = _append_impl(
            tail, delta_a, _grid_jnp(grid), state, metric,
            strategy=strategy, cost=0.0, block=epi_block, D=D,
            full_cover=False, K_new=K_new)
        m = _finalize_impl(new_metric, jnp.float32(carry.n_bars + D),
                           ppy=252)
        return tuple(m) + tuple(
            new_tail[k] for k in sorted(new_tail)) + tuple(
            new_state[k] for k in sorted(new_state)) + tuple(
            new_metric[k] for k in sorted(new_metric))

    args = [{k: np.asarray(v) for k, v in carry.tail.items()}, delta,
            {k: np.asarray(v) for k, v in carry.state.items()},
            {k: np.asarray(v) for k, v in carry.metric.items()}]
    return fn, args


# ---------------------------------------------------------------------------
# dbxcert probes: the certified streaming cones with LABELED outputs
# ---------------------------------------------------------------------------

# Carry accumulators that are f32 sums/holds of exact small integers by
# the documented carry contract (positions in {-1,0,1}, bool-cast win/
# active counts, |Δpos| turnover increments): dbxcert seeds the append
# form's inputs with this integrality hint so the analyzer can prove the
# int-exact merge guarantee the parity tests pin empirically.
_INTEGRAL_CARRY_KEYS = frozenset(
    {"wins", "active", "turnover", "pos_last"})


def certify_probe(strategy: str, *, form: str, epilogue: str | None = None):
    """``(fn, args, integral_keys)`` for dbxcert (analysis.certify).

    ``fn(*args)`` traces one certified cone of ``strategy`` — ``form``
    is ``"build_carry"`` (scan form over the full tiny panel from the
    zero state) or ``"append_step"`` (recurrent form over a ΔT slice
    from the stored carry) — returning a DICT so every output is
    addressable by a stable label in ``numerics.contract.json``
    (``metrics/<name>`` the 9 public metrics, ``metric/<k>`` the
    accumulators, ``state/<k>`` family signal state, ``tail/<k>`` the
    raw-input tail). The epilogue substrate is passed explicitly (no env
    mutation); the un-jitted impl bodies are traced so a live edit is
    always seen. ``integral_keys`` names input dict keys the analyzer
    may assume integer-valued (the carry contract's hints)."""
    if form not in ("build_carry", "append_step"):
        raise ValueError(f"unknown certify form {form!r}")
    carry, delta, grid, base_fields = _probe_inputs(strategy)
    gj = _grid_jnp(grid)

    def _label(m: Metrics, metric: dict, state: dict, extra: dict) -> dict:
        out = {f"metrics/{k}": getattr(m, k) for k in Metrics._fields}
        out.update({f"metric/{k}": v for k, v in metric.items()})
        out.update({f"state/{k}": v for k, v in state.items()})
        out.update(extra)
        return out

    if form == "append_step":
        D = _PROBE_DELTA_BARS
        block = _block(D, epilogue)
        K_new = int(carry.tail["close"].shape[-1])

        def fn(tail, delta_a, state, metric):
            new_tail, new_state, new_metric = _append_impl(
                tail, delta_a, gj, state, metric, strategy=strategy,
                cost=0.001, block=block, D=D, full_cover=False,
                K_new=K_new)
            m = _finalize_impl(new_metric, jnp.float32(carry.n_bars + D),
                               ppy=252)
            return _label(m, new_metric, new_state,
                          {f"tail/{k}": v for k, v in new_tail.items()})

        args = [{k: np.asarray(v) for k, v in carry.tail.items()},
                dict(delta),
                {k: np.asarray(v) for k, v in carry.state.items()},
                {k: np.asarray(v) for k, v in carry.metric.items()}]
        return fn, args, _INTEGRAL_CARRY_KEYS

    T = int(base_fields["close"].shape[-1])
    block = _block(T, epilogue)

    def fn(fields):
        metric, state = _build_impl(fields, gj, strategy=strategy,
                                    cost=0.001, block=block)
        m = _finalize_impl(metric, jnp.float32(T), ppy=252)
        return _label(m, metric, state, {})

    return fn, [dict(base_fields)], frozenset()
