"""Streaming backtests: persistable carry checkpoints + O(ΔT) appends.

The scan-form/recurrent-form duality (PAPERS.md "Compiler-First State
Space Duality and Portable O(1) Autoregressive Caching") applied to the
sweep engine: the cold sweep runs the scan form over the full T-bar
panel once and leaves behind a per-(panel_digest, strategy, param-block)
:class:`~.recurrent.StreamCarry`; every appended ΔT-bar slice then
advances that carry with the recurrent form (:func:`~.recurrent
.append_step`) in O(ΔT) work and O(1) state — no full reprice. The
carry is digest-keyed and device-resident like a KV cache
(:class:`~.store.CarryStore`, the streaming twin of the worker's
PanelCache), with a host-serialized level that survives device-level
eviction.

The carry halves (``recurrent``/``store``) build the per-family carry
machinery on import and are LAZY-loaded (PEP 562): the dispatcher's
live fan-out tier (``serve/``) imports :mod:`.delta` — the metric-delta
extraction over DBXM blocks that sits behind every push — and a pure
control-plane process must not pay the carry-registry import wall for
a byte diff.
Attribute access (``streaming.build_carry``, ``streaming.CarryStore``)
and direct submodule imports keep working unchanged; they simply load
the heavy halves at first touch.
"""

from .delta import metric_delta  # noqa: F401

# name -> submodule holding it; resolved on first attribute access.
_LAZY = {name: "recurrent" for name in (
    "StreamCarry", "append_step", "build_carry", "carry_from_bytes",
    "carry_to_bytes", "finalize", "stream_fields", "stream_key",
    "supports_strategy", "tail_bars")}
_LAZY.update({name: "store" for name in (
    "CarryStore", "carry_cache_max_bytes")})

__all__ = ["metric_delta", *_LAZY]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f".{mod}", __name__), name)
    globals()[name] = value   # cache: later access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
