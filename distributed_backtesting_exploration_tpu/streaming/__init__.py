"""Streaming backtests: persistable carry checkpoints + O(ΔT) appends.

The scan-form/recurrent-form duality (PAPERS.md "Compiler-First State
Space Duality and Portable O(1) Autoregressive Caching") applied to the
sweep engine: the cold sweep runs the scan form over the full T-bar
panel once and leaves behind a per-(panel_digest, strategy, param-block)
:class:`~.recurrent.StreamCarry`; every appended ΔT-bar slice then
advances that carry with the recurrent form (:func:`~.recurrent
.append_step`) in O(ΔT) work and O(1) state — no full reprice. The
carry is digest-keyed and device-resident like a KV cache
(:class:`~.store.CarryStore`, the streaming twin of the worker's
PanelCache), with a host-serialized level that survives device-level
eviction.
"""

from .recurrent import (  # noqa: F401
    StreamCarry, append_step, build_carry, carry_from_bytes,
    carry_to_bytes, finalize, stream_fields, stream_key,
    supports_strategy, tail_bars)
from .store import CarryStore, carry_cache_max_bytes  # noqa: F401
