"""Digest-keyed carry-checkpoint cache (the streaming twin of PanelCache).

Two levels, mirroring the worker panel cache's shape so the eviction and
accounting semantics cannot drift (both ride ``panel_store.ByteLRU``):

- **device level**: the live :class:`~.recurrent.StreamCarry` with its
  jax arrays resident — a hit advances in O(ΔT) with zero host work;
- **host level**: the serialized checkpoint bytes
  (:func:`~.recurrent.carry_to_bytes`) — survives device-level eviction;
  a hit deserializes and re-primes the device level. Restoring is
  lossless, so an append after evict+restore bit-matches an append to
  the never-evicted carry (tested).

Keys are ``(panel_digest, stream_key)`` — the content address of the
panel state the carry summarizes plus the parameter-block digest
(:func:`~.recurrent.stream_key`), so a checkpoint can never serve a
different grid/cost/strategy than it was built for. Bounded per level by
``DBX_CARRY_CACHE_MB`` (default 64). Eviction of both levels is not an
error: the worker falls back to a full reprice and re-checkpoints.

Thread-safe: the worker control thread may probe while the compute
thread serves.
"""

from __future__ import annotations

import os
import threading

from .. import obs
from ..rpc.panel_store import ByteLRU
from . import recurrent

_DEFAULT_CARRY_MB = 64


def carry_cache_max_bytes() -> int:
    """Per-level carry-cache budget, read lazily (import-time env capture
    would pin the knob before tests/operators can set it)."""
    return int(float(os.environ.get("DBX_CARRY_CACHE_MB",
                                    _DEFAULT_CARRY_MB)) * 1024 * 1024)


class CarryStore:
    """Two-level LRU of ``(panel_digest, stream_key) -> StreamCarry``."""

    def __init__(self, max_bytes: int | None = None,
                 registry: "obs.Registry | None" = None):
        self.max_bytes = (carry_cache_max_bytes() if max_bytes is None
                          else int(max_bytes))
        self._lock = threading.Lock()
        self._device = ByteLRU(self.max_bytes)    # put() passes nbytes
        self._host = ByteLRU(self.max_bytes)      # serialized bytes
        reg = registry or obs.get_registry()
        self._c_hits = {
            lvl: reg.counter("dbx_carry_cache_hits_total",
                             help="carry-checkpoint cache hits by level "
                                  "(device=resident carry, host="
                                  "deserialized checkpoint)", level=lvl)
            for lvl in ("host", "device")}
        self._c_misses = {
            lvl: reg.counter("dbx_carry_cache_misses_total",
                             help="carry-checkpoint cache misses by level",
                             level=lvl)
            for lvl in ("host", "device")}
        self._g_bytes = reg.gauge(
            "dbx_carry_cache_bytes",
            help="approximate bytes resident in the carry cache "
                 "(device + host levels)")

    def _publish_bytes(self) -> None:
        self._g_bytes.set(self._device.bytes + self._host.bytes)

    def get(self, key) -> "recurrent.StreamCarry | None":
        with self._lock:
            carry = self._device.get(key)
        if carry is not None:
            self._c_hits["device"].inc()
            return carry
        self._c_misses["device"].inc()
        with self._lock:
            blob = self._host.get(key)
        if blob is None:
            self._c_misses["host"].inc()
            return None
        self._c_hits["host"].inc()
        carry = recurrent.carry_from_bytes(blob)
        with self._lock:
            if key in self._device:
                # A racer re-primed (or a fresh append re-checkpointed)
                # the key in the deserialize window: the resident carry
                # is same-or-newer, and overwriting it with this
                # thread's older copy would silently lose the advance
                # (dbxlint atomicity — check-then-act across release).
                return self._device.get(key)
            # Re-prime the device level so the next append skips the
            # deserialize too.
            self._device.put(key, carry, carry.nbytes)
            self._publish_bytes()
        return carry

    def put(self, key, carry: "recurrent.StreamCarry") -> None:
        blob = recurrent.carry_to_bytes(carry)
        with self._lock:
            self._device.put(key, carry, carry.nbytes)
            self._host.put(key, blob)
            self._publish_bytes()

    def evict_device(self, key) -> None:
        """Drop the device-resident copy only (tests + memory pressure
        hooks); the host checkpoint keeps the state restorable."""
        with self._lock:
            self._device.pop(key)
            self._publish_bytes()

    def stats(self) -> dict:
        with self._lock:
            return {"device_carries": len(self._device),
                    "device_bytes": self._device.bytes,
                    "host_carries": len(self._host),
                    "host_bytes": self._host.bytes,
                    "max_bytes": self.max_bytes}
