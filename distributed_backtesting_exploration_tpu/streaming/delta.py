"""Metric-delta extraction between successive stream results.

The live fan-out tier (``serve/``) pushes each stream's full DBXM block
— the bit-matching contract is on the block, and at ``n_params x 9``
float32s it is already small — but a thin client following thousands of
streams wants to know WHICH ticks actually moved something before it
diffs anything. This module computes that summary dispatcher-side, from
the result cache's previous block: the number of param lanes whose
metrics changed under the appended bars. It rides on the carry-advance
output (every pushed block is a finalized carry), hence its home in
``streaming/``; the diff itself is plain numpy over the DBXM codec the
dispatcher already speaks, so the push path never touches the
recurrent/fused kernel machinery (``streaming/__init__`` lazy-loads
those halves for the same reason).

NaN-aware: a lane that stays NaN (e.g. sharpe of an all-flat param
combo) is UNCHANGED — the naive ``a != b`` would report every NaN lane
as moved on every tick.
"""

from __future__ import annotations

import numpy as np

from ..rpc import wire


def metric_delta(prev: bytes | None, new: bytes) -> tuple[int, int]:
    """``(changed, total)`` param lanes between two DBXM blocks.

    ``changed`` is the count of param lanes where ANY metric differs
    bitwise-as-values (NaN == NaN counts as equal); ``total`` is the
    lane count of ``new``. With no ``prev`` block — a stream's first
    result, or the previous entry evicted from the result cache —
    ``changed`` is -1 (the wire's "nothing to diff against" marker,
    distinct from 0 = "tick moved nothing"). A ``prev`` block whose
    shape no longer matches (the stream was rebuilt under a different
    grid) also reports -1 rather than a fabricated diff.
    """
    m_new = wire.metrics_from_bytes(new)
    total = int(np.asarray(m_new[0]).size)
    if prev is None:
        return -1, total
    try:
        m_prev = wire.metrics_from_bytes(prev)
    except ValueError:
        return -1, total
    if int(np.asarray(m_prev[0]).size) != total:
        return -1, total
    moved = np.zeros(total, dtype=bool)
    for a, b in zip(m_prev, m_new):
        a = np.asarray(a)
        b = np.asarray(b)
        moved |= (a != b) & ~(np.isnan(a) & np.isnan(b))
    return int(moved.sum()), total
