"""Digest-seeded scenario synthesis: on-device synthetic OHLCV panels.

Adversarial load tests and scenario-diversity sweeps (stress regimes, gap
opens, vol shocks) do not need terabytes of files: a synthetic panel is a
pure function of ``(base panel_digest, generator params)``, so a scenario
job ships as a spec and materializes dispatcher-side through the
content-addressed :class:`~..rpc.panel_store.PanelStore` — the PR-5
digest-only dispatch then moves it like any other panel, and the worker
needs zero changes beyond the cache it already has.
"""

from .synth import (  # noqa: F401
    ScenarioParams, generate, max_bars, scenario_panel_bytes,
    scenario_seed, seed_to_int64, seed_words)
