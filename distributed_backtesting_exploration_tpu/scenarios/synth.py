"""Jittable block-bootstrap + regime-switching OHLCV generator.

The generator resamples a REAL base panel's per-bar geometry — joint
(close return, open gap, upper wick, lower wick, volume) tuples — in
contiguous blocks (block bootstrap preserves short-range autocorrelation,
the thing iid resampling destroys and mean-reversion strategies feed on),
then modulates volatility through a K-regime Markov-switching scan and
optionally injects gap-open shocks. Bars reconstruct multiplicatively, so
``high >= max(open, close) >= min(open, close) >= low > 0`` holds by
construction.

Reproducibility contract: the effective PRNG seed is
``scenario_seed(base_digest, params)`` — a pure function of the base
panel's content address and the canonical parameter encoding — and the
generator itself is a deterministic jitted program of fixed shapes, so
``scenario_panel_bytes(base_bytes, params)`` returns byte-identical
panels (hence the SAME content digest) on every call, across dispatcher
restarts, and for every worker that re-derives it. The output digest is
therefore a pure function of the ``(digest, params)`` spec, which is what
lets a scenario sweep dispatch as specs instead of payloads.

Everything host-side (env knobs, validation, seed derivation) happens
OUTSIDE the jitted core — dbxlint's trace-time-env rule holds.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import data as data_mod

_DEFAULT_MAX_BARS = 1 << 20

# Markov regime persistence: P(stay in the current vol regime per bar).
# Fixed rather than a knob — regime dwell time (~25 bars) is a property
# of the generator family; diversity comes from the seeded chain itself.
_REGIME_PERSIST = 0.96


def max_bars() -> int:
    """Safety cap on generated panel length (``DBX_SCENARIO_MAX_BARS``),
    read lazily — a malicious/typo'd spec must fail the one job, not OOM
    the dispatcher."""
    return int(os.environ.get("DBX_SCENARIO_MAX_BARS", _DEFAULT_MAX_BARS))


@dataclasses.dataclass(frozen=True)
class ScenarioParams:
    """Generator parameters — the ``params`` half of a scenario spec.

    ``seed`` is a user sequence number (scenario i of a diversity sweep),
    folded into the effective seed together with the base digest and
    every other field."""

    n_bars: int = 0          # output length; 0 = the base panel's length
    block: int = 16          # bootstrap block length in bars
    regimes: int = 2         # K Markov vol regimes; <= 1 disables switching
    vol_scale: float = 2.0   # top-regime vol multiplier (span 1/s .. s)
    shock: float = 0.0       # per-bar probability of a gap-open shock
    seed: int = 0            # scenario sequence number

    def canonical(self) -> str:
        """Canonical encoding — THE string hashed into the effective
        seed; key order and float formatting are fixed so equal specs
        can never hash apart."""
        d = dataclasses.asdict(self)
        return json.dumps({k: d[k] for k in sorted(d)},
                          separators=(",", ":"), sort_keys=True)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "ScenarioParams":
        """Build from a (journal) dict; unknown keys — e.g. the record's
        ``base`` digest — are ignored."""
        fields = {f.name for f in dataclasses.fields(ScenarioParams)}
        return ScenarioParams(**{k: v for k, v in d.items() if k in fields})


def scenario_seed(base_digest: str, params: ScenarioParams) -> int:
    """64-bit effective seed: blake2b of ``base_digest | canonical
    params``. Same hash family as the panel digest itself — one seed per
    distinct spec, stable across processes."""
    h = hashlib.blake2b(
        f"{base_digest}|{params.canonical()}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "little")


def seed_words(seed: int) -> tuple[int, int]:
    """The ``(lo, hi)`` int31 words of a 64-bit effective seed — exactly
    the pair :func:`generate` folds into the PRNG key. Shared with the
    in-trace scenario megakernel (``ops.fused.fused_scenario_sweep``),
    which receives the words as traced scalars, so both paths derive
    bit-identical threefry keys from one spec."""
    return seed & 0x7FFFFFFF, (seed >> 31) & 0x7FFFFFFF


def seed_to_int64(seed: int) -> int:
    """Two's-complement wrap of an unsigned 64-bit effective seed into
    the signed int64 range ``ScenarioSpec.seed`` can carry.
    :func:`seed_words` masks fixed bit fields, so it returns the SAME
    words for ``seed`` and ``seed_to_int64(seed)`` — the wire roundtrip
    cannot skew key derivation."""
    return seed - (1 << 64) if seed >= (1 << 63) else seed


def _gen_impl(open_, high, low, close, volume, vol_scale, shock, key, *,
              n_bars: int, block: int, regimes: int):
    """The traced generator (fixed shapes; one compile per
    (base_T, n_bars, block, regimes) bucket). The un-jitted body is the
    dbxcert digest-cone trace target (``certify_probe``) — the output
    digest's determinism contract is certified over exactly this
    program.

    The panel builds BLOCK BY BLOCK: bars arrive in bootstrap-block
    chunks from a `lax.scan` over the block index, with block ``b``'s
    randomness drawn from ``fold_in(key, b)`` and only O(block) state
    (regime, cumulative log level) carried across. That schedule is the
    load-bearing part of the scenario megakernel: the fused sweep path
    replays exactly this scan in-trace, regenerating each T-block of the
    panel on the fly inside the sweep launch, and per-block keying makes
    block ``b`` independent of everything but ``(key, b)`` — the bytes
    the host path emits and the blocks the fused path regenerates are
    identical by construction, not by parallel maintenance."""
    f32 = jnp.float32
    c_prev = close[:-1]
    ret = jnp.log(close[1:] / c_prev)              # (Tb,)
    gap = jnp.log(open_[1:] / c_prev)
    hi = jnp.abs(jnp.log(high[1:] / jnp.maximum(open_[1:], close[1:])))
    lo = jnp.abs(jnp.log(jnp.minimum(open_[1:], close[1:]) / low[1:]))
    t_base = ret.shape[0]
    # Sigma of the base return stream sizes the ~5-sigma gap shocks.
    sigma = jnp.std(ret)
    n_blocks = -(-n_bars // block)
    if regimes > 1:
        # K log-spaced vol multipliers spanning 1/vol_scale .. vol_scale;
        # the regime path is a persistent Markov chain (scan) so vol
        # clusters instead of flickering per bar.
        mult = jnp.exp(jnp.linspace(-1.0, 1.0, regimes)
                       * jnp.log(jnp.maximum(vol_scale, 1.0 + 1e-6)))

    def block_step(carry, b):
        state, log_level = carry
        kb = jax.random.fold_in(key, b)
        k_start, k_sw, k_pick, k_shock, k_mag = jax.random.split(kb, 5)
        start = jax.random.randint(k_start, (), 0,
                                   max(t_base - block + 1, 1))
        idx = jnp.minimum(start + jnp.arange(block), t_base - 1)
        if regimes > 1:
            u = jax.random.uniform(k_sw, (block,))
            cand = jax.random.randint(k_pick, (block,), 0, regimes)

            def step(s, xs):
                u_t, cand_t = xs
                s = jnp.where(u_t < (1.0 - _REGIME_PERSIST), cand_t, s)
                return s, s

            state, path = jax.lax.scan(step, state, (u, cand))
            scale = mult[path].astype(f32)
        else:
            scale = jnp.ones((block,), f32)
        # Gap-open shocks: rare (p = shock) jumps of ~5 sigma of the
        # base return stream, applied to the open gap AND the close
        # return so the level shift persists past the bar (a gap that
        # mean-reverted by the close would not stress latch/stop logic).
        hit = jax.random.uniform(k_shock, (block,)) < shock
        mag = jax.random.normal(k_mag, (block,)) * 5.0 * sigma
        jump = jnp.where(hit, mag, 0.0)

        b_ret = ret[idx] * scale + jump
        b_gap = gap[idx] * scale + jump
        cum = log_level + jnp.cumsum(b_ret)
        close_b = close[0] * jnp.exp(cum)
        prev = close[0] * jnp.exp(
            jnp.concatenate([log_level[None], cum[:-1]]))
        open_b = prev * jnp.exp(b_gap)
        body_hi = jnp.maximum(open_b, close_b)
        body_lo = jnp.minimum(open_b, close_b)
        high_b = body_hi * jnp.exp(hi[idx] * scale)
        low_b = body_lo * jnp.exp(-lo[idx] * scale)
        vol_b = volume[1:][idx]
        return ((state, cum[-1]),
                (open_b, high_b, low_b, close_b, vol_b))

    _, chunks = jax.lax.scan(block_step, (jnp.int32(0), f32(0.0)),
                             jnp.arange(n_blocks))
    return tuple(c.reshape(-1)[:n_bars].astype(f32) for c in chunks)


_gen_core = functools.partial(
    jax.jit, static_argnames=("n_bars", "block", "regimes"))(_gen_impl)


def generate(base: data_mod.OHLCV, params: ScenarioParams,
             seed: int) -> data_mod.OHLCV:
    """One synthetic single-ticker panel from ``base`` (fields shaped
    ``(T,)``) under ``params`` and the 64-bit effective ``seed``."""
    if base.close.ndim != 1:
        raise ValueError("generate takes a single ticker, fields "
                         "shaped (T,)")
    if base.n_bars < 2:
        raise ValueError("scenario base needs >= 2 bars "
                         f"(got {base.n_bars})")
    n_bars = int(params.n_bars) or base.n_bars
    cap = max_bars()
    if not 1 <= n_bars <= cap:
        raise ValueError(f"scenario n_bars {n_bars} outside [1, {cap}] "
                         "(DBX_SCENARIO_MAX_BARS)")
    block = max(int(params.block), 1)
    regimes = max(int(params.regimes), 1)
    lo, hi = seed_words(seed)
    key = jax.random.fold_in(jax.random.PRNGKey(lo), hi)
    fields = _gen_core(
        *(jnp.asarray(np.asarray(f), jnp.float32) for f in base),
        jnp.float32(params.vol_scale), jnp.float32(params.shock), key,
        n_bars=n_bars, block=block, regimes=regimes)
    return data_mod.OHLCV(*(np.asarray(f) for f in fields))


def certify_probe():
    """``(fn, args, integral_keys)`` for dbxcert: the generation digest
    cone on tiny pinned shapes. The scenario digest scheme is sound only
    if this program is run-to-run deterministic for a fixed (seed,
    params) — the certifier asserts no *nondet*-class primitive ever
    reaches these outputs (float association is fine: the program always
    evaluates in its own fixed order)."""
    base = data_mod.synthetic_ohlcv(1, 48, seed=3)
    key = jax.random.fold_in(jax.random.PRNGKey(7), 11)

    def fn(open_, high, low, close, volume, key):
        o, h, l, c, v = _gen_impl(
            open_, high, low, close, volume, jnp.float32(2.0),
            jnp.float32(0.1), key, n_bars=16, block=4, regimes=2)
        return {"open": o, "high": h, "low": l, "close": c, "volume": v}

    args = [np.asarray(getattr(base, f)[0], np.float32)
            for f in ("open", "high", "low", "close", "volume")]
    args.append(np.asarray(key))
    return fn, args, frozenset()


def scenario_panel_bytes(base_bytes: bytes,
                         params: ScenarioParams) -> bytes:
    """DBX1 wire bytes of the scenario panel for ``(base_bytes, params)``
    — deterministic, so the digest of the RESULT is a pure function of
    ``(digest(base_bytes), params)``: the property that lets the
    dispatcher re-materialize an evicted scenario panel (or a restarted
    dispatcher re-derive it) under the same content address it first
    stamped."""
    base_digest = hashlib.blake2b(base_bytes, digest_size=16).hexdigest()
    base = data_mod.from_wire_bytes(base_bytes)
    series = generate(base, params,
                      scenario_seed(base_digest, params))
    return data_mod.to_wire_bytes(series)
