"""Market-data representation and codecs.

The reference ships raw CSV file bytes inside ``Job.File``
(reference ``proto/backtesting.proto:15``) and gzips the server->worker
direction to shrink them (reference ``README.md:18``). Here the wire format is
a compact binary OHLCV block (:func:`to_wire_bytes`) — smaller than gzipped
CSV and decodable straight into device-ready float32 arrays with zero text
parsing on the hot path — while CSV remains supported for ingest parity.

Layout rules (TPU-first):

- every field is a separate ``(..., T)`` array (struct-of-arrays). A packed
  ``(..., T, 5)`` channels-last layout would waste a 128-lane tile on a
  5-wide minor axis; struct-of-arrays keeps the long time axis on lanes.
- ragged ticker histories are padded at the *end* to a lane-friendly multiple
  (default 128) with the last close repeated — so padded bars have zero
  return — plus an explicit validity mask (:func:`pad_and_stack`).
"""

from __future__ import annotations

import io
import struct
from typing import NamedTuple, Sequence

import numpy as np

_WIRE_MAGIC = b"DBX1"
_FIELDS = ("open", "high", "low", "close", "volume")


class OHLCV(NamedTuple):
    """Struct-of-arrays OHLCV batch; each field shaped ``(..., T)``."""

    open: np.ndarray
    high: np.ndarray
    low: np.ndarray
    close: np.ndarray
    volume: np.ndarray

    @property
    def n_bars(self) -> int:
        return self.close.shape[-1]


def synthetic_ohlcv(
    n_tickers: int,
    n_bars: int,
    *,
    seed: int = 0,
    s0: float = 100.0,
    mu: float = 0.08,
    sigma: float = 0.25,
    periods_per_year: int = 252,
    dtype=np.float32,
) -> OHLCV:
    """Geometric-Brownian-motion OHLCV panel, shape ``(n_tickers, n_bars)``.

    Deterministic in ``seed``; used for fixtures and benchmarks in place of
    the reference's eight hardcoded stock CSVs (reference
    ``src/server/main.rs:198-209``).
    """
    rng = np.random.default_rng(seed)
    dt = 1.0 / periods_per_year
    z = rng.standard_normal((n_tickers, n_bars))
    log_ret = (mu - 0.5 * sigma**2) * dt + sigma * np.sqrt(dt) * z
    close = s0 * np.exp(np.cumsum(log_ret, axis=-1))
    open_ = np.concatenate([np.full((n_tickers, 1), s0), close[:, :-1]], axis=-1)
    wick = np.abs(rng.standard_normal((2, n_tickers, n_bars))) * sigma * np.sqrt(dt)
    high = np.maximum(open_, close) * (1.0 + wick[0])
    low = np.minimum(open_, close) * (1.0 - wick[1])
    volume = np.exp(rng.normal(12.0, 1.0, (n_tickers, n_bars)))
    return OHLCV(*(a.astype(dtype) for a in (open_, high, low, close, volume)))


# ---------------------------------------------------------------------------
# CSV codec (ingest parity with the reference's CSV job payloads)
# ---------------------------------------------------------------------------

def to_csv_bytes(series: OHLCV) -> bytes:
    """Encode a single ticker (fields shaped ``(T,)``) as OHLCV CSV bytes."""
    if series.close.ndim != 1:
        raise ValueError("to_csv_bytes takes a single ticker, fields shaped (T,)")
    buf = io.StringIO()
    buf.write("open,high,low,close,volume\n")
    for row in zip(*(np.asarray(getattr(series, f), np.float64) for f in _FIELDS)):
        buf.write(",".join(repr(float(v)) for v in row) + "\n")
    return buf.getvalue().encode()


def from_csv_bytes(data: bytes, *, dtype=np.float32) -> OHLCV:
    """Decode OHLCV CSV bytes (header with open/high/low/close/volume columns).

    Tolerates extra columns (e.g. a leading date column) by name-matching the
    header, like typical adjusted-split stock CSVs. Uses the native C++
    decoder (``cpp/dbx_core.cc``) when built — this is the dispatcher's
    payload hot path — falling back to the pure-Python parser.
    """
    if dtype == np.float32:
        try:
            from ..runtime import _core
            if _core.available():
                return OHLCV(*_core.csv_decode(data))
        except Exception:
            # Fall through: the Python parser is the semantic reference and
            # accepts some inputs the strict native parser rejects (e.g.
            # padded numeric fields); truly bad CSVs fail below with the
            # canonical error.
            pass
    text = data.decode()
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty CSV payload")
    header = [h.strip().lower() for h in lines[0].split(",")]
    cols = {name: header.index(name) for name in _FIELDS if name in header}
    missing = [f for f in _FIELDS if f not in cols]
    if missing:
        raise ValueError(f"CSV missing columns: {missing}; header={header}")
    rows = [ln.split(",") for ln in lines[1:]]
    out = {}
    for name, j in cols.items():
        out[name] = np.asarray([float(r[j]) for r in rows], dtype=dtype)
    return OHLCV(**out)


def to_parquet_bytes(series: OHLCV) -> bytes:
    """Encode a single ticker as a Parquet file (pyarrow).

    The columnar twin of :func:`to_csv_bytes` — same five named columns,
    f64 values — for fleets whose market data lives in Parquet lakes
    rather than CSV dumps.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    if series.close.ndim != 1:
        raise ValueError(
            "to_parquet_bytes takes a single ticker, fields shaped (T,)")
    table = pa.table({f: np.asarray(getattr(series, f), np.float64)
                      for f in _FIELDS})
    sink = io.BytesIO()
    pq.write_table(table, sink)
    return sink.getvalue()


def from_parquet_bytes(data: bytes, *, dtype=np.float32) -> OHLCV:
    """Decode a Parquet file's OHLCV columns (name-matched, case-insensitive;
    extra columns such as a date index are tolerated, like the CSV
    decoder)."""
    try:
        import pyarrow.parquet as pq
    except ImportError as e:
        # pyarrow is an optional dependency (only Parquet payloads need it);
        # a raw ModuleNotFoundError here would read as a framework bug and —
        # worse — escape the dispatcher's (OSError, ValueError) bad-payload
        # triage and crash the intake thread instead of failing the one job.
        raise ValueError(
            "pyarrow is required to decode Parquet payloads but is not "
            "installed on this host; install pyarrow or feed CSV/DBX1 "
            f"files instead ({e})") from e

    try:
        table = pq.read_table(io.BytesIO(data))
    except Exception as e:
        raise ValueError(f"not a readable Parquet file: {e}") from e
    by_name = {name.strip().lower(): i
               for i, name in enumerate(table.column_names)}
    missing = [f for f in _FIELDS if f not in by_name]
    if missing:
        raise ValueError(f"Parquet missing columns: {missing}; "
                         f"columns={table.column_names}")
    return OHLCV(*(np.asarray(table.column(by_name[f]).to_numpy(),
                              dtype=dtype) for f in _FIELDS))


# ---------------------------------------------------------------------------
# Binary wire codec (replaces CSV-text-over-gzip on the job data plane)
# ---------------------------------------------------------------------------

def to_wire_bytes(series: OHLCV) -> bytes:
    """Pack one ticker into the compact binary block: magic, T, 5 x f32[T]."""
    if series.close.ndim != 1:
        raise ValueError("to_wire_bytes takes a single ticker, fields shaped (T,)")
    T = series.n_bars
    parts = [_WIRE_MAGIC, struct.pack("<I", T)]
    for f in _FIELDS:
        parts.append(np.ascontiguousarray(
            getattr(series, f), dtype="<f4").tobytes())
    return b"".join(parts)


def from_wire_bytes(data: bytes) -> OHLCV:
    """Decode the binary block produced by :func:`to_wire_bytes`."""
    # len check BEFORE unpack: a 4-7 byte block with valid magic must fail
    # with the contract's ValueError, not struct.error (differential-fuzzed
    # against the native decoder, which reports these as bad-magic too).
    if len(data) < 8 or data[:4] != _WIRE_MAGIC:
        raise ValueError("bad magic; not a DBX1 OHLCV block")
    (T,) = struct.unpack_from("<I", data, 4)
    need = 8 + 4 * 5 * T
    if len(data) < need:
        raise ValueError(f"truncated OHLCV block: {len(data)} < {need}")
    fields = []
    off = 8
    for _ in _FIELDS:
        fields.append(np.frombuffer(data, dtype="<f4", count=T, offset=off).copy())
        off += 4 * T
    return OHLCV(*fields)


def splice_wire_bytes(base: bytes, delta: bytes) -> bytes:
    """Extend a DBX1 panel by a DBX1 delta slice: per-field concatenation.

    The streaming-append primitive (AppendBars): deterministic, so
    replaying a journaled ``delta`` chain after a dispatcher restart
    reconstructs byte-identical extended panels — and hence the same
    content digests the first run stamped.
    """
    b = from_wire_bytes(base)
    d = from_wire_bytes(delta)
    if d.n_bars < 1:
        raise ValueError("empty delta slice")
    return to_wire_bytes(OHLCV(*(
        np.concatenate([np.asarray(bf), np.asarray(df)])
        for bf, df in zip(b, d))))


def splice_cone_probe():
    """``(fn, args, integral_keys)`` for dbxcert: the array-dataflow cone
    of :func:`splice_wire_bytes` — per-field concatenation, nothing else.

    The splice's digest guarantee (replayed chains reproduce the SAME
    extended-panel digests) holds because the operation is pure data
    movement: the certifier pins every output of this cone to the
    *exact* class with a zero boundary census, so an edit that slips any
    arithmetic (rescaling, re-encoding, accumulation) into the splice
    path fails the digest-determinism gate. The byte-level codec framing
    around it is covered by the wire round-trip tests."""
    import jax.numpy as jnp  # lazy: utils.data stays numpy-only otherwise

    base = synthetic_ohlcv(1, 12, seed=5)
    delta = synthetic_ohlcv(1, 4, seed=6)

    def fn(b, d):
        return {f: jnp.concatenate([b[f], d[f]], axis=-1)
                for f in _FIELDS}

    args = [{f: np.asarray(getattr(base, f)[0], np.float32)
             for f in _FIELDS},
            {f: np.asarray(getattr(delta, f)[0], np.float32)
             for f in _FIELDS}]
    return fn, args, frozenset()


def pad_and_stack(
    series: Sequence[OHLCV], *, lane_multiple: int = 128
) -> tuple[OHLCV, np.ndarray, np.ndarray]:
    """Stack ragged per-ticker series into one padded device-ready batch.

    Returns ``(batch, lengths, mask)`` where ``batch`` fields are
    ``(n_tickers, T_pad)`` with ``T_pad`` the max length rounded up to
    ``lane_multiple``; padding repeats each ticker's final bar (so padded
    returns are exactly 0 and cannot create phantom PnL) and ``mask`` is the
    ``(n_tickers, T_pad)`` validity mask.
    """
    lengths = np.asarray([s.n_bars for s in series], np.int32)
    t_max = int(lengths.max())
    t_pad = -(-t_max // lane_multiple) * lane_multiple
    n = len(series)
    cols = {f: np.zeros((n, t_pad), np.float32) for f in _FIELDS}
    for i, s in enumerate(series):
        for f in _FIELDS:
            a = np.asarray(getattr(s, f), np.float32)
            cols[f][i, : a.shape[0]] = a
            cols[f][i, a.shape[0]:] = a[-1]
    mask = np.arange(t_pad)[None, :] < lengths[:, None]
    return OHLCV(**cols), lengths, mask
