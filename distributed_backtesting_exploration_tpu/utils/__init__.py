"""Utilities: market-data codecs, config, logging, counters, native bindings."""

from .data import (  # noqa: F401
    OHLCV,
    synthetic_ohlcv,
    to_csv_bytes,
    from_csv_bytes,
    to_wire_bytes,
    from_wire_bytes,
    pad_and_stack,
)
# NOTE: `utils.trace` is a deprecation shim over `..obs` and is no longer
# imported eagerly — importing it emits a DeprecationWarning, which an
# unconditional package-level import would fire on every process start.
