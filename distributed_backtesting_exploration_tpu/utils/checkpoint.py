"""Checkpoint/restore for long-running sweep state (orbax-backed).

The dispatcher's JSONL journal (``rpc/journal.py``) makes the *queue*
crash-durable; this module makes long *computations* resumable — the result
store of a large sweep campaign or the per-window state of a long
walk-forward — via orbax's atomic array checkpointing (the reference has no
checkpointing at all; its own README lists the resulting data loss,
reference ``README.md:80``).
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import numpy as np

from ..ops.metrics import Metrics


def save_metrics(path: str, metrics: Metrics, *,
                 meta: Mapping[str, Any] | None = None) -> None:
    """Atomically checkpoint a Metrics pytree (plus small metadata)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    payload = {name: np.asarray(f) for name, f in zip(Metrics._fields, metrics)}
    if meta:
        payload["_meta"] = dict(meta)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, payload, force=True)


def load_metrics(path: str) -> tuple[Metrics, dict]:
    """Restore a Metrics checkpoint; returns ``(metrics, meta)``."""
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        payload = ckptr.restore(os.path.abspath(path))
    meta = payload.pop("_meta", {})
    return Metrics(*(payload[name] for name in Metrics._fields)), dict(meta)


class SweepCheckpointer:
    """Incremental result store for a chunked sweep campaign.

    Usage: iterate your (ticker-block x param-block) work list; after each
    block call :meth:`add`; on restart :meth:`done` tells you which block
    ids to skip. Results live as one checkpoint per block id under ``root``
    (atomic per block, so a crash mid-save never corrupts earlier blocks).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _block_path(self, block_id: str) -> str:
        return os.path.join(self.root, f"block-{block_id}")

    def done(self) -> set[str]:
        out = set()
        for name in os.listdir(self.root):
            if name.startswith("block-"):
                out.add(name[len("block-"):])
        return out

    def add(self, block_id: str, metrics: Metrics,
            meta: Mapping[str, Any] | None = None) -> None:
        save_metrics(self._block_path(block_id), metrics, meta=meta)

    def get(self, block_id: str) -> tuple[Metrics, dict]:
        return load_metrics(self._block_path(block_id))
