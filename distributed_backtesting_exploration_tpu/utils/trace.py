"""Tracing / profiling utilities.

The reference's observability is structured logging plus one hand-timed
phase (file reads timed with an Instant and logged, reference
``src/server/main.rs:167-175``). This module keeps that per-phase timing
pattern as a context manager and adds the TPU-native profiler: a context
that wraps ``jax.profiler`` and writes a TensorBoard-loadable trace of XLA
kernels.
"""

from __future__ import annotations

import contextlib
import logging
import time

log = logging.getLogger("dbx.trace")


@contextlib.contextmanager
def timed(name: str, *, logger: logging.Logger = log, level=logging.INFO):
    """Log the wall-clock duration of a phase: ``with timed("decode"): ...``"""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.log(level, "%s took %.1fms", name,
                   1e3 * (time.perf_counter() - t0))


@contextlib.contextmanager
def device_profile(logdir: str):
    """Capture a jax.profiler trace (XLA kernel timeline) under ``logdir``.

    View with TensorBoard's profile plugin. On the remote-proxy TPU backend
    host-side events still capture; device traces need a directly-attached
    chip.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Running throughput meter: the ``backtests/sec`` counter surfaced by
    the dispatcher's GetStats — usable worker-side for per-batch logs."""

    def __init__(self):
        self.t0 = time.monotonic()
        self.units = 0.0

    def add(self, n: float) -> None:
        self.units += n

    @property
    def rate(self) -> float:
        return self.units / max(time.monotonic() - self.t0, 1e-9)
