"""DEPRECATED: moved to :mod:`distributed_backtesting_exploration_tpu.obs`.

``utils.trace`` grew into the unified observability layer — the span API,
metrics registry, JSONL event log and ``/metrics`` surface all live under
``obs`` now (DESIGN.md "Observability"). This shim re-exports the old
names unchanged and is kept for ONE release; import from ``..obs`` (or
``..obs.trace``) instead.
"""

from __future__ import annotations

import warnings

from ..obs.trace import (  # noqa: F401
    StepTimer, device_profile, span, timed)

warnings.warn(
    "distributed_backtesting_exploration_tpu.utils.trace is deprecated; "
    "use distributed_backtesting_exploration_tpu.obs (same names: timed, "
    "device_profile, StepTimer, plus the new span/registry APIs)",
    DeprecationWarning, stacklevel=2)
