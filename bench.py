"""Benchmark suite: (ticker x param) backtests/sec on one chip, per config.

Headline workload = the BASELINE.json north star (configs[1]): a 500-ticker
SMA-crossover sweep over 5 years of daily bars with a 2,000-point
(fast, slow) grid — 1,000,000 full backtests (indicators, positions, PnL,
9 summary metrics) per sweep call, via the fused Pallas kernel. The suite
also measures configs[2]-[4] and the rest of the fused family: Bollinger
(500 x 1k (window, k), hysteresis and band-touch), momentum, Donchian
(close and high/low channels), stochastic %K, VWAP reversion, RSI, MACD,
TRIX, OBV trend, rolling-OLS pairs (1k pairs x 500 (lookback, z_entry)),
and walk-forward
(12 refit windows x param grid), plus an
``e2e`` config that pushes the headline workload
through a loopback gRPC dispatcher + worker (decode, RPC and metric
reporting included), printing a per-config line to stderr.

Baseline: the reference's worker processes jobs serially at 1 job/sec (its
compute slot sleeps 1 s per job — reference ``src/worker/process.rs:23``), so
``vs_baseline`` is the raw speedup over 1 backtest/sec.

Methodology: the first call (compile) is excluded; a further untimed warm-up
round absorbs the remote-proxy dispatch pipeline's cold start (the first ~10
dispatches pay full round-trip latency before pipelining engages — measured
4M/s cold vs 16M/s warm for the same program). Timed iterations chain into a
device-side accumulator so every sweep executes, synchronized once at the end.
A persistent compilation cache under .jax_cache cuts fresh-process compiles.

CROSS-RUN variance caveat (r4, measured): the same config can move +-35%
between bench invocations on this environment's tunneled chip (keltner
measured 7.25 M/s inside one full-suite run and 11.35 M/s isolated
minutes later, identical code). Only BACK-TO-BACK A/B runs in one sitting
are trustworthy for optimization decisions; a single full-suite run's
per-config spread is bounded-reliable for the big picture (kernel-family
ratios, bound attribution) but not for ~20% deltas. An r4 experiment that
"fixed" keltner's apparent 41% utilization by fusing its 25 per-window
EMA prep ladders into one stacked ladder measured FASTER against the bad
baseline and 16-19% SLOWER in a controlled A/B (per-window loop wins for
keltner/rsi/macd prep); the loop stays, and the roofline's per-run
utilization figures should be read with that error bar.

Prints ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": "backtests/sec", "vs_baseline": N,
     "configs": {name: rate, ...}}

``--verify`` mode instead runs fused-vs-generic parity for every fused
kernel (SMA, Bollinger hysteresis + band-touch, momentum, Donchian close +
high/low, stochastic, VWAP, RSI, MACD, TRIX, OBV, pairs) ON THE CHIP
and prints one JSON line with max relative error and the argmax/entry flip
rates (the knife-edge MXU caveat, plus MACD's in-kernel-ladder vs
associative_scan caveat — quantified fresh each round and asserted
against per-kernel error budgets: over-budget kernels FAIL the run; see
DESIGN.md "Fused-kernel error budgets").

Env overrides (local smoke runs): DBX_BENCH_TICKERS, DBX_BENCH_BARS,
DBX_BENCH_PARAMS, DBX_BENCH_ITERS, DBX_BENCH_WARMUP, DBX_BENCH_CPU=1 to
force the CPU platform, DBX_BENCH_CONFIGS=comma list to subset configs.
"""

import json
import os
import sys
import time


def _setup_jax():
    if os.environ.get("DBX_BENCH_CPU") == "1":
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    if os.environ.get("DBX_BENCH_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.environ.get(
        "DBX_BENCH_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return jax


# Approximate TPU v5e (v5 lite) peaks for the roofline model below. MXU
# f32 = the 197 bf16 TFLOP/s spec divided by the 6-pass HIGHEST-precision
# schedule every selection matmul here uses. The VPU figure is an estimate
# (1024 lanes x ~2.6 f32 ops/cycle effective); these are for RELATIVE bound
# attribution — "which resource caps this kernel" — not absolute gospel.
V5E_PEAKS = {"vpu": 4.0e12, "mxu": 3.3e13, "hbm": 8.1e11}
ROOFLINE: dict = {}


def _roofline_note(name, rate: float, n_bars: int, model: dict | None):
    """Per-kernel utilization string from a (vpu ops, mxu flops, hbm bytes)
    per-cell-bar model; records the figures for the bench JSON."""
    if not model:
        return ""
    cell_bars = rate * n_bars
    util = {res: cell_bars * per / V5E_PEAKS[res]
            for res, per in model.items()}
    bound = max(util, key=util.get)
    ROOFLINE[name] = {**{f"{r}_util": round(u, 3) for r, u in util.items()},
                      "bound": bound,
                      "vpu_ops_per_cell_bar": model.get("vpu", 0)}
    parts = ", ".join(f"{r.upper()} {100 * u:.0f}%" for r, u in util.items())
    return f" | {parts} of v5e peak -> {bound.upper()}-bound"


def _measure(run, n_backtests: int, *, iters: int, warmup: int, name: str,
             n_bars: int = 0, model: dict | None = None):
    """Compile + warm the dispatch pipeline, then time ``iters`` chained runs."""
    import jax.numpy as jnp
    import numpy as np

    t0 = time.perf_counter()
    out = run()
    first = np.asarray(out.sharpe)
    assert np.isfinite(first).all(), f"{name}: non-finite metrics"
    compile_s = time.perf_counter() - t0

    acc = jnp.float32(0.0)
    for _ in range(warmup):
        acc = acc + jnp.sum(run().sharpe)
    float(acc)  # sync

    t0 = time.perf_counter()
    acc = jnp.float32(0.0)
    for _ in range(iters):
        acc = acc + jnp.sum(run().sharpe)
    acc_val = float(acc)   # the synchronizing fetch — must not be elided
    elapsed = time.perf_counter() - t0
    assert np.isfinite(acc_val), f"{name}: non-finite accumulator"
    rate = n_backtests * iters / elapsed
    print(f"bench[{name}]: compile {compile_s:.1f}s, {iters}x {n_backtests} "
          f"backtests in {elapsed:.3f}s -> {rate/1e6:.2f}M/s"
          f"{_roofline_note(name, rate, n_bars, model)}", file=sys.stderr)
    return rate


def main():
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from distributed_backtesting_exploration_tpu.models import base, pairs
    from distributed_backtesting_exploration_tpu.ops import fused
    from distributed_backtesting_exploration_tpu.parallel import (
        sweep, walkforward)
    from distributed_backtesting_exploration_tpu.utils import data

    # The e2e/dispatch configs push thousands of traced jobs (~5 spans
    # each) through the in-process loop; the default 512-span ring would
    # retain only the last ~100 jobs for the end-of-run "timeline"
    # digest. Size it through the DBX_SPAN_RING knob (setdefault: an
    # operator's explicit choice wins) to hold a full config's spans —
    # torn heads are dropped and counted by summarize_spans either way.
    from distributed_backtesting_exploration_tpu import obs as _obs
    os.environ.setdefault("DBX_SPAN_RING", "32768")
    _obs.configure_ring()

    n_tickers = int(os.environ.get("DBX_BENCH_TICKERS", 500))
    n_bars = int(os.environ.get("DBX_BENCH_BARS", 1260))      # 5y daily
    n_params = int(os.environ.get("DBX_BENCH_PARAMS", 2000))
    iters = int(os.environ.get("DBX_BENCH_ITERS", 10))
    warmup = int(os.environ.get("DBX_BENCH_WARMUP", 12))
    only = os.environ.get("DBX_BENCH_CONFIGS")
    only = set(only.split(",")) if only else None

    dev = jax.devices()[0]
    print(f"bench: device={dev.device_kind} tickers={n_tickers} "
          f"bars={n_bars} params={n_params}", file=sys.stderr)

    ohlcv = data.synthetic_ohlcv(n_tickers, n_bars, seed=0)
    panel = type(ohlcv)(*(jax.device_put(jnp.asarray(f), dev) for f in ohlcv))
    rates: dict[str, float] = {}

    def enabled(name):
        return only is None or name in only

    # --- Roofline models: per-(cell, bar) resource counts read off the
    # kernel structure in ops/fused.py. The per-bar recurrences (equity
    # cumsum + running-peak cummax, the band machines' 3-state compose)
    # default to the SINGLE-PASS carry scan over T-blocks (`_equity_scan`
    # / `_compose3_path`): per-row ladder work is log2(B) rounds for the
    # static scan block B instead of log2(T_pad), plus a few carry-combine
    # ops per row. `DBX_EPILOGUE=ladder` restores the full-T ladders (and
    # this model follows it, so the A/B's utilization figures stay honest):
    #   metrics tail  = ~26 reduction/PnL ops + 2 ladders x 2 ops/round + 7
    #   3-state prefix compose (band/latch machines) = 9 ops/round + 2
    #   in-kernel EMA ladder (MACD signal line)      = 5 ops/round (full-T:
    #     the signal EMA's per-lane decay is not blocked — carry state is a
    #     multiply chain, not a select)
    # MXU = 2 FLOP x W_pad contraction per selection matmul per cell-bar
    # (HIGHEST precision — the peak constant already folds the 6-pass
    # schedule). HBM = the (W_pad x T_pad) table stream amortized over
    # P_pad lanes, times (1 + prep passes over table-shaped intermediates).
    rounds = max(int(np.ceil(np.log2(max(n_bars, 2)))), 1)
    _epi = fused._resolve_epilogue(None)      # same arg>env>default chain
    if _epi == "ladder":
        tail_rounds = compose_rounds = rounds
        tail_fix = compose_fix = 0
    else:
        # The kernels' own block pick (incl. the doubling past 256 blocks
        # for long-context shapes) — the model must not re-derive it.
        _blk = fused._scan_block(-(-n_bars // 8) * 8, _epi)
        tail_rounds = compose_rounds = max(int(np.ceil(np.log2(_blk))), 1)
        tail_fix, compose_fix = 7, 2          # carry combines per row
    TAIL = 26 + 4 * tail_rounds + tail_fix    # shared metrics tail
    LADDER3 = 9 * compose_rounds + compose_fix  # band/latch 3-state compose

    def _model(vpu, n_distinct_w, p, *, w_align=8, selections=1,
               prep_passes=3):
        w_pad = -(-max(n_distinct_w, 1) // w_align) * w_align
        p_pad = -(-max(p, 1) // 128) * 128
        return {"vpu": float(vpu),
                "mxu": 2.0 * selections * w_pad,
                "hbm": 4.0 * w_pad * (1 + prep_passes) / p_pad}

    # --- configs[1] headline: fused SMA-crossover sweep -------------------
    if enabled("sma_fused"):
        n_fast = 20
        n_slow = max(n_params // n_fast, 1)
        grid = sweep.product_grid(
            fast=jnp.arange(5, 5 + n_fast, dtype=jnp.float32),
            slow=jnp.arange(30, 30 + 2 * n_slow, 2, dtype=jnp.float32))
        fa, sl = np.asarray(grid["fast"]), np.asarray(grid["slow"])
        if os.environ.get("DBX_BENCH_GENERIC") == "1":
            strat = base.get_strategy("sma_crossover")
            chunk = int(os.environ.get("DBX_BENCH_CHUNK", 100))

            def run_sma():
                return sweep.chunked_sweep(panel, strat, grid,
                                           param_chunk=chunk, cost=1e-3)
        else:
            def run_sma():
                return fused.fused_sma_sweep(panel.close, fa, sl, cost=1e-3)

        # The default substrate is the in-kernel (VMEM-scratch) table
        # (ops/fused.py `_kernel_inline`, DBX_SMA_TABLE=hbm for the A/B
        # twin): no XLA table passes and no table HBM stream, so the HBM
        # term drops to the cs + returns rows and the VPU term gains the
        # per-ticker table build amortized over the param lanes
        # (~4 ops x W_pad x 8/occupancy / P_pad per cell-bar).
        sma_inline = os.environ.get("DBX_SMA_TABLE", "inline") == "inline"
        n_w = np.unique(np.r_[fa, sl]).size
        sma_model = _model(TAIL + 4, n_w, fa.size, w_align=128,
                           prep_passes=0 if sma_inline else 3)
        if sma_inline:
            p_pad = -(-fa.size // 128) * 128
            sma_model["hbm"] = 4.0 * 2 / p_pad
            sma_model["vpu"] += 4.0 * n_w * 8 / p_pad
        rates["sma_fused"] = _measure(
            run_sma, n_tickers * sweep.grid_size(grid), iters=iters,
            warmup=warmup, name="sma_fused", n_bars=n_bars,
            model=sma_model)

    # --- roofline_stages: where the SMA kernel's cycles actually go -------
    # (VERDICT r4 weak #4: no kernel exceeds 2/3 of its modeled VPU
    # roofline and the residual was unexplained.) Cut-down variants of the
    # EXACT headline kernel — same grid, same block specs, same table prep
    # — with later stages removed, so consecutive deltas attribute wall
    # time to (selection matmul + sign) / (PnL prep) / (equity+peak shift
    # ladders) / (reductions + pack). Results feed the DESIGN.md roofline
    # accounting table; ROOFLINE["sma_stages"] records them in BENCH JSON.
    if enabled("roofline_stages"):
        import functools

        from distributed_backtesting_exploration_tpu.ops import fused as F
        from distributed_backtesting_exploration_tpu.ops.metrics import (
            Metrics)

        pl = F.pl
        pltpu = F.pltpu
        n_fast = 20
        n_slow = max(n_params // n_fast, 1)
        sgrid = sweep.product_grid(
            fast=jnp.arange(5, 5 + n_fast, dtype=jnp.float32),
            slow=jnp.arange(30, 30 + 2 * n_slow, 2, dtype=jnp.float32))
        sfa = np.asarray(sgrid["fast"])
        ssl = np.asarray(sgrid["slow"])
        windows, onehot_d, warm = F._grid_setup(
            sfa.astype(np.float32).tobytes(),
            ssl.astype(np.float32).tobytes())
        T_pad = F._round_up(n_bars, 8)
        W_pad = onehot_d.shape[0]
        P_real = sfa.shape[0]
        interp = jax.default_backend() != "tpu"

        def stage_kernel(r_ref, sma_ref, od_ref, warm_ref, out_ref,
                         *, stage, lanes):
            # Mirrors ops.fused._kernel exactly through the requested
            # stage, then writes a cheap stand-in tile so every variant
            # has identical I/O (measurement scaffolding only — results
            # are NOT metrics except for the "full*" stages). ``lanes``
            # parameterizes the per-cell param-block width (the block-
            # shape experiment: fewer, wider cells amortize per-cell
            # fixed overhead).
            T_pd = r_ref.shape[1]
            r = r_ref[0]
            sma = sma_ref[0]                  # (W_pad, T_pad) — W-major
            if stage == "touch":
                # Stream the table through VMEM without the contraction:
                # isolates DMA + per-cell overhead from MXU time.
                out_ref[0, 0] = jnp.full(
                    (F._METRIC_ROWS, lanes), jnp.sum(sma), jnp.float32)
                return
            d = jax.lax.dot_general(
                sma, od_ref[:], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pd, lanes), 0)
            if stage == "matmul":
                out_ref[0, 0] = jnp.broadcast_to(
                    jnp.sum(d, axis=0)[None, :], (F._METRIC_ROWS, lanes))
                return
            warm_v = warm_ref[0, :][None, :]
            valid = t_idx >= (warm_v.astype(jnp.int32) - 1)
            pos = jnp.where(valid, jnp.sign(d), 0.0)
            if stage == "signal":
                out_ref[0, 0] = jnp.broadcast_to(
                    jnp.sum(pos * r, axis=0)[None, :],
                    (F._METRIC_ROWS, lanes))
                return
            tr = n_bars
            if stage in ("full", "full_ladder"):
                # The REAL shipped tail (shared code, not a copy): this
                # variant IS ops.fused._kernel end to end. "full" runs the
                # shipped single-pass carry scan; "full_ladder" the
                # O(T log T) fallback substrate — their delta over
                # no_ladders is the scan's win on this exact kernel.
                out_ref[0, 0] = F._metrics_tail(
                    pos, r, t_idx, tr, cost=1e-3, ppy=252,
                    epilogue="scan" if stage == "full" else "ladder")
                return
            # no_ladders: the shipped reductions with the two shift
            # ladders (equity cumsum + running-peak cummax) replaced by
            # one pass each — a deliberately CUT variant isolating ladder
            # cost from reduction cost (scaffolding, not metrics).
            row_ok = t_idx < tr
            pos_last = F._row_at(pos, tr, t_idx, keepdims=True)
            pos = jnp.where(row_ok, pos, pos_last)
            prev = F._shift_down(pos, 1, 0.0)
            net = prev * r - 1e-3 * jnp.abs(pos - prev)
            n_f = jnp.asarray(tr, jnp.float32)
            s1 = jnp.sum(net, axis=0)
            s2 = jnp.sum(net * net, axis=0)
            meanv = s1 / n_f
            var = jnp.maximum(s2 / n_f - meanv * meanv, 0.0)
            std = jnp.sqrt(var)
            down = jnp.minimum(net, 0.0)
            dstd = jnp.sqrt(jnp.sum(down * down, axis=0) / n_f)
            active = (jnp.abs(prev) > 0) & row_ok
            wins = (net > 0) & active
            hit = jnp.sum(wins.astype(jnp.float32), axis=0) / (
                jnp.sum(active.astype(jnp.float32), axis=0) + 1e-12)
            turnover = jnp.sum(jnp.abs(pos - prev), axis=0)
            rows = jnp.stack([s1, s2, meanv, std, dstd, hit,
                              turnover, std, s1], axis=0)
            out_ref[0, 0] = jnp.concatenate(
                [rows, jnp.zeros((F._METRIC_ROWS - 9, lanes),
                                 jnp.float32)], axis=0)

        @functools.partial(jax.jit, static_argnames=("stage", "lanes"))
        def stage_call(close, *, stage, lanes=128):
            # THE shipped table prep (shared code, not a copy).
            close_p = F._pad_last(close, T_pad)
            tbl = F._sma_table(close_p, windows, W_pad)
            r3 = F._rets3(close_p)
            P_pad = onehot_d.shape[1]
            if stage == "prep":
                # XLA table construction alone, no pallas call: the
                # host-program share of the "matmul" base.
                return jnp.broadcast_to(
                    jnp.sum(tbl, axis=(1, 2))[:, None] + r3[:, 0, :],
                    (close.shape[0], P_pad))[:, :P_real]
            nb = P_pad // lanes
            out = pl.pallas_call(
                functools.partial(stage_kernel, stage=stage, lanes=lanes),
                grid=(close.shape[0], nb),
                in_specs=[
                    pl.BlockSpec((1, T_pad, 1), lambda i, j: (i, 0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, W_pad, T_pad), lambda i, j: (i, 0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((W_pad, lanes), lambda i, j: (0, j),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(
                    (1, 1, F._METRIC_ROWS, lanes),
                    lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct(
                    (close.shape[0], nb, F._METRIC_ROWS, lanes),
                    jnp.float32),
                interpret=interp,
            )(r3, tbl, F._const(onehot_d), F._const(warm))
            return jnp.reshape(out[:, :, 0, :],
                               (close.shape[0], P_pad))[:, :P_real]

        stage_times = {}
        n_bt = n_tickers * P_real
        P_pad_all = onehot_d.shape[1]
        cases = [(stage, lanes)
                 for stage, lanes in
                 [("prep", 128), ("touch", 128), ("matmul", 128),
                  ("signal", 128), ("no_ladders", 128),
                  ("full", 128), ("full_ladder", 128), ("full", 256),
                  ("full", 512), ("full", 1024), ("no_ladders", 512)]
                 # Non-headline DBX_BENCH_PARAMS values can make P_pad
                 # smaller than (or not a multiple of) a lane case; skip
                 # those instead of building a zero/ragged grid.
                 if P_pad_all >= lanes and P_pad_all % lanes == 0]
        for stage, lanes in cases:
            def run_stage(stage=stage, lanes=lanes):
                from types import SimpleNamespace
                return SimpleNamespace(
                    sharpe=stage_call(panel.close, stage=stage,
                                      lanes=lanes))
            # _measure asserts finite sharpe; stand-in tiles are finite.
            rate = _measure(run_stage, n_bt, iters=iters, warmup=warmup,
                            name=f"sma_stage_{stage}_l{lanes}")
            stage_times[f"{stage}_l{lanes}"] = n_bt / rate  # s per sweep

        def _attribution(times, full_key="full_l128"):
            # Consecutive-delta attribution shared by the SMA and
            # bollinger scaffolds. "full" runs the SHIPPED carry-scan
            # epilogue, so ladders_delta_pct is the scan's residual share
            # (the acceptance metric); "full_ladder" re-times the
            # O(T log T) fallback substrate on the same kernel, so
            # ladder_fallback_delta_pct is the old 47.6%-class number and
            # epilogue_scan_speedup their end-to-end ratio.
            full_s = times[full_key]
            out = {
                "selection_matmul_pct": round(
                    100 * times["matmul_l128"] / full_s, 1),
                "signal_delta_pct": round(
                    100 * (times["signal_l128"] - times["matmul_l128"])
                    / full_s, 1),
                "reductions_delta_pct": round(
                    100 * (times["no_ladders_l128"] - times["signal_l128"])
                    / full_s, 1),
                "ladders_delta_pct": round(
                    100 * (full_s - times["no_ladders_l128"]) / full_s, 1),
            }
            if "full_ladder_l128" in times:
                out["ladder_fallback_delta_pct"] = round(
                    100 * (times["full_ladder_l128"]
                           - times["no_ladders_l128"])
                    / times["full_ladder_l128"], 1)
                out["epilogue_scan_speedup"] = round(
                    times["full_ladder_l128"] / full_s, 3)
            return out

        full_s = stage_times["full_l128"]
        attribution = _attribution(stage_times)
        if "full_l512" in stage_times:   # skipped for small P_pad
            attribution["wide_block_speedup_l512"] = round(
                full_s / stage_times["full_l512"], 2)
        # Shipped-path A/Bs on top of the cut stages, both through the
        # real fused_sma_sweep at its auto-picked block width: the
        # in-kernel (VMEM-scratch) table vs the XLA/HBM table (justifies
        # DBX_SMA_TABLE's "inline" default), and the carry-scan epilogue
        # vs the ladder fallback (justifies DBX_EPILOGUE's "scan").
        for mode in ("hbm", "inline"):
            rate = _measure(
                lambda mode=mode: fused.fused_sma_sweep(
                    panel.close, sfa, ssl, cost=1e-3, table=mode),
                n_bt, iters=iters, warmup=warmup,
                name=f"sma_table_{mode}")
            stage_times[f"table_{mode}"] = n_bt / rate
        attribution["inline_table_speedup"] = round(
            stage_times["table_hbm"] / stage_times["table_inline"], 3)
        for mode in ("ladder", "scan"):
            rate = _measure(
                lambda mode=mode: fused.fused_sma_sweep(
                    panel.close, sfa, ssl, cost=1e-3, epilogue=mode),
                n_bt, iters=iters, warmup=warmup,
                name=f"sma_epilogue_{mode}")
            stage_times[f"epilogue_{mode}"] = n_bt / rate
        attribution["epilogue_e2e_speedup"] = round(
            stage_times["epilogue_ladder"] / stage_times["epilogue_scan"],
            3)
        ROOFLINE["sma_stages"] = {
            **{f"{k}_s_per_sweep": round(v, 6)
               for k, v in stage_times.items()},
            **attribution}
        rates["roofline_stages_full"] = n_bt / full_s
        print(f"bench[roofline_stages]: attribution {attribution}",
              file=sys.stderr)

        # --- bollinger stages: the band-machine twin of the SMA scaffold.
        # Same cut-down discipline over the EXACT hbm-table bollinger
        # kernel (z-table prep shared with _fused_boll_call): attributes
        # the selection matmul, the 3-state compose machine (scan vs
        # ladder substrate), and the metrics-tail ladders for the family
        # whose vpu_ops_per_cell_bar sat at 179 vs the sign kernels' 76.
        n_win, n_k = 20, max(min(n_params, 1000) // 20, 1)
        rgrid = sweep.product_grid(
            k=jnp.linspace(0.5, 3.0, n_k).astype(jnp.float32),
            window=jnp.arange(10, 10 + 2 * n_win, 2, dtype=jnp.float32))
        rw = np.asarray(rgrid["window"])
        rk = np.asarray(rgrid["k"])
        bwindows, b_onehot, b_klanes, b_warm = F._boll_grid_setup(
            rw.astype(np.float32).tobytes(), rk.tobytes())
        bT_pad = F._round_up(n_bars, 128)
        bW_pad = b_onehot.shape[0]
        bP_real = rw.shape[0]
        bP_pad = b_onehot.shape[1]

        def boll_stage_kernel(r_ref, z_ref, ow_ref, k_ref, warm_ref,
                              out_ref, *, stage, lanes):
            # Mirrors ops.fused._boll_kernel through the requested stage
            # (same scaffolding contract as stage_kernel above).
            T_pd = r_ref.shape[1]
            r = r_ref[0]
            zt = z_ref[0]                    # (W_pad, T_pad) z-table
            if stage == "touch":
                out_ref[0, 0] = jnp.full(
                    (F._METRIC_ROWS, lanes), jnp.sum(zt), jnp.float32)
                return
            z = jax.lax.dot_general(
                zt, ow_ref[:], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            t_idx = jax.lax.broadcasted_iota(jnp.int32, (T_pd, lanes), 0)
            if stage == "matmul":
                out_ref[0, 0] = jnp.broadcast_to(
                    jnp.sum(z, axis=0)[None, :], (F._METRIC_ROWS, lanes))
                return
            warm_v = warm_ref[0, :][None, :]
            valid = t_idx >= (warm_v.astype(jnp.int32) - 1)
            k_l = k_ref[0, :][None, :]
            epi = "ladder" if stage.endswith("_ladder") else "scan"
            pos = F._band_ladder(z, valid, k_l, 0.0, epi)
            if stage in ("signal", "signal_ladder"):
                # + the 3-state compose machine (the band family's extra
                # cost vs sign kernels), in the requested substrate.
                out_ref[0, 0] = jnp.broadcast_to(
                    jnp.sum(pos * r, axis=0)[None, :],
                    (F._METRIC_ROWS, lanes))
                return
            tr = n_bars
            if stage in ("full", "full_ladder"):
                out_ref[0, 0] = F._metrics_tail(pos, r, t_idx, tr,
                                                cost=1e-3, ppy=252,
                                                epilogue=epi)
                return
            # no_ladders: compose machine (scan) + the one-pass reduction
            # stand-ins of the SMA scaffold.
            row_ok = t_idx < tr
            pos_last = F._row_at(pos, tr, t_idx, keepdims=True)
            pos = jnp.where(row_ok, pos, pos_last)
            prev = F._shift_down(pos, 1, 0.0)
            net = prev * r - 1e-3 * jnp.abs(pos - prev)
            n_f = jnp.asarray(tr, jnp.float32)
            s1 = jnp.sum(net, axis=0)
            s2 = jnp.sum(net * net, axis=0)
            meanv = s1 / n_f
            std = jnp.sqrt(jnp.maximum(s2 / n_f - meanv * meanv, 0.0))
            turnover = jnp.sum(jnp.abs(pos - prev), axis=0)
            rows = jnp.stack([s1, s2, meanv, std, std, s1,
                              turnover, std, s1], axis=0)
            out_ref[0, 0] = jnp.concatenate(
                [rows, jnp.zeros((F._METRIC_ROWS - 9, lanes),
                                 jnp.float32)], axis=0)

        @functools.partial(jax.jit, static_argnames=("stage", "lanes"))
        def boll_stage_call(close, *, stage, lanes=128):
            # THE shipped hbm z-table prep (_fused_boll_call's op order,
            # via the shared cumsum-window closures).
            close_p = F._pad_last(close, bT_pad)
            T = close.shape[1]
            xc = close_p - jnp.mean(close_p[:, :T], axis=1, keepdims=True)
            w_col, w_f, t_row, windowed_sum, _ = F._cumsum_window_tools(
                bwindows, bT_pad)
            m = windowed_sum(close_p) / w_f
            s1 = windowed_sum(xc)
            s2 = windowed_sum(xc * xc)
            var = jnp.maximum((s2 - s1 * s1 / w_f) / w_f, 0.0)
            z_tbl = (close_p[:, None, :] - m) / (jnp.sqrt(var) + 1e-12)
            z_tbl = F._pad_w(
                jnp.where((t_row >= w_col - 1)[None], z_tbl, 0.0), bW_pad)
            r3 = F._rets3(close_p)
            if stage == "prep":
                return jnp.broadcast_to(
                    jnp.sum(z_tbl, axis=(1, 2))[:, None] + r3[:, 0, :],
                    (close.shape[0], bP_pad))[:, :bP_real]
            nb = bP_pad // lanes
            out = pl.pallas_call(
                functools.partial(boll_stage_kernel, stage=stage,
                                  lanes=lanes),
                grid=(close.shape[0], nb),
                in_specs=[
                    pl.BlockSpec((1, bT_pad, 1), lambda i, j: (i, 0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, bW_pad, bT_pad),
                                 lambda i, j: (i, 0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((bW_pad, lanes), lambda i, j: (0, j),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, lanes), lambda i, j: (0, j),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(
                    (1, 1, F._METRIC_ROWS, lanes),
                    lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct(
                    (close.shape[0], nb, F._METRIC_ROWS, lanes),
                    jnp.float32),
                interpret=interp,
            )(r3, z_tbl, F._const(b_onehot), F._const(b_klanes),
              F._const(b_warm))
            return jnp.reshape(out[:, :, 0, :],
                               (close.shape[0], bP_pad))[:, :bP_real]

        boll_times = {}
        b_bt = n_tickers * bP_real
        for stage in ("prep", "touch", "matmul", "signal", "signal_ladder",
                      "no_ladders", "full", "full_ladder"):
            def run_bstage(stage=stage):
                from types import SimpleNamespace
                return SimpleNamespace(
                    sharpe=boll_stage_call(panel.close, stage=stage))
            rate = _measure(run_bstage, b_bt, iters=iters, warmup=warmup,
                            name=f"boll_stage_{stage}_l128")
            boll_times[f"{stage}_l128"] = b_bt / rate
        boll_attr = _attribution(boll_times)
        boll_attr["compose_delta_pct"] = round(
            100 * (boll_times["signal_l128"] - boll_times["matmul_l128"])
            / boll_times["full_l128"], 1)
        boll_attr["compose_ladder_delta_pct"] = round(
            100 * (boll_times["signal_ladder_l128"]
                   - boll_times["matmul_l128"])
            / boll_times["full_l128"], 1)
        for mode in ("ladder", "scan"):
            rate = _measure(
                lambda mode=mode: fused.fused_bollinger_sweep(
                    panel.close, rw, rk, cost=1e-3, epilogue=mode),
                b_bt, iters=iters, warmup=warmup,
                name=f"boll_epilogue_{mode}")
            boll_times[f"epilogue_{mode}"] = b_bt / rate
        boll_attr["epilogue_e2e_speedup"] = round(
            boll_times["epilogue_ladder"] / boll_times["epilogue_scan"], 3)
        ROOFLINE["bollinger_stages"] = {
            **{f"{k}_s_per_sweep": round(v, 6)
               for k, v in boll_times.items()},
            **boll_attr}
        rates["roofline_stages_boll_full"] = b_bt / boll_times["full_l128"]
        print(f"bench[roofline_stages/bollinger]: attribution {boll_attr}",
              file=sys.stderr)

    # --- configs[2]: fused Bollinger (window, k) --------------------------
    if enabled("bollinger_fused"):
        n_win, n_k = 20, max(min(n_params, 1000) // 20, 1)
        bgrid = sweep.product_grid(
            k=jnp.linspace(0.5, 3.0, n_k).astype(jnp.float32),
            window=jnp.arange(10, 10 + 2 * n_win, 2, dtype=jnp.float32))
        bw = np.asarray(bgrid["window"])
        bk = np.asarray(bgrid["k"])

        def run_boll():
            return fused.fused_bollinger_sweep(panel.close, bw, bk, cost=1e-3)

        rates["bollinger_fused"] = _measure(
            run_boll, n_tickers * sweep.grid_size(bgrid), iters=iters,
            warmup=warmup, name="bollinger_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 10, np.unique(bw).size, bw.size))

    if enabled("bollinger_touch_fused"):
        n_win, n_k = 20, max(min(n_params, 1000) // 20, 1)
        tgrid = sweep.product_grid(
            k=jnp.linspace(0.5, 3.0, n_k).astype(jnp.float32),
            window=jnp.arange(10, 10 + 2 * n_win, 2, dtype=jnp.float32))
        tw = np.asarray(tgrid["window"])
        tk = np.asarray(tgrid["k"])

        def run_touch():
            return fused.fused_bollinger_touch_sweep(panel.close, tw, tk,
                                                     cost=1e-3)

        rates["bollinger_touch_fused"] = _measure(
            run_touch, n_tickers * sweep.grid_size(tgrid), iters=iters,
            warmup=warmup, name="bollinger_touch_fused", n_bars=n_bars,
            model=_model(TAIL + 8, np.unique(tw).size, tw.size))

    # --- momentum / donchian: the round-3 single-window-axis kernels ------
    if enabled("momentum_fused"):
        mlbs = np.tile(np.arange(5, 130, dtype=np.float32),
                       max(n_params // 125, 1))

        def run_mom():
            return fused.fused_momentum_sweep(panel.close, mlbs, cost=1e-3)

        # Default substrate is the in-kernel past-close table (VMEM
        # scratch, `_mom_kernel_inline`; measured +4% median / +8% best
        # over the XLA-gather table on this grid): no table HBM stream.
        mom_inline = os.environ.get("DBX_MOM_TABLE", "inline") == "inline"
        mom_model = _model(TAIL + 4, np.unique(mlbs).size, mlbs.size,
                           prep_passes=0 if mom_inline else 2)
        if mom_inline:
            mom_p_pad = -(-mlbs.size // 128) * 128
            # 3 streamed rows per ticker: returns column, close column
            # (the tail's `close - past`), and the close-row aux the
            # builder rotates (SMA streams only cs + returns = 2).
            mom_model["hbm"] = 4.0 * 3 / mom_p_pad
            mom_model["vpu"] += 4.0 * np.unique(mlbs).size * 8 / mom_p_pad
        rates["momentum_fused"] = _measure(
            run_mom, n_tickers * len(mlbs), iters=iters, warmup=warmup,
            name="momentum_fused", n_bars=n_bars,
            model=mom_model)

    if enabled("donchian_fused"):
        dwins = np.tile(np.arange(10, 135, dtype=np.float32),
                        max(min(n_params, 1000) // 125, 1))

        def run_don():
            return fused.fused_donchian_sweep(panel.close, dwins, cost=1e-3)

        rates["donchian_fused"] = _measure(
            run_don, n_tickers * len(dwins), iters=iters, warmup=warmup,
            name="donchian_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 10, np.unique(dwins).size,
                         dwins.size))

    if enabled("donchian_hl_fused"):
        hwins = np.tile(np.arange(10, 135, dtype=np.float32),
                        max(min(n_params, 1000) // 125, 1))

        def run_don_hl():
            return fused.fused_donchian_hl_sweep(
                panel.close, panel.high, panel.low, hwins, cost=1e-3)

        rates["donchian_hl_fused"] = _measure(
            run_don_hl, n_tickers * len(hwins), iters=iters, warmup=warmup,
            name="donchian_hl_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 10, np.unique(hwins).size,
                         hwins.size, prep_passes=4))

    # --- vwap: the volume-consuming band-machine kernel -------------------
    if enabled("vwap_fused"):
        n_win, n_k = 20, max(min(n_params, 1000) // 20, 1)
        vgrid = sweep.product_grid(
            k=jnp.linspace(0.5, 3.0, n_k).astype(jnp.float32),
            window=jnp.arange(10, 10 + 2 * n_win, 2, dtype=jnp.float32))
        vw = np.asarray(vgrid["window"])
        vk = np.asarray(vgrid["k"])

        def run_vwap():
            return fused.fused_vwap_sweep(panel.close, panel.volume, vw, vk,
                                          cost=1e-3)

        rates["vwap_fused"] = _measure(
            run_vwap, n_tickers * sweep.grid_size(vgrid), iters=iters,
            warmup=warmup, name="vwap_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 10, np.unique(vw).size, vw.size,
                         prep_passes=4))

    if enabled("keltner_fused"):
        kgrid = sweep.product_grid(
            k=jnp.linspace(1.0, 3.0, max(min(n_params, 1000) // 25, 1)
                           ).astype(jnp.float32),
            window=jnp.arange(5, 55, 2, dtype=jnp.float32))
        kw = np.asarray(kgrid["window"])
        kk = np.asarray(kgrid["k"])

        def run_kelt():
            return fused.fused_keltner_sweep(
                panel.close, panel.high, panel.low, kw, kk, cost=1e-3)

        rates["keltner_fused"] = _measure(
            run_kelt, n_tickers * sweep.grid_size(kgrid), iters=iters,
            warmup=warmup, name="keltner_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 10, np.unique(kw).size, kw.size,
                         prep_passes=4))

    if enabled("stochastic_fused"):
        sgrid = sweep.product_grid(
            band=jnp.linspace(10, 40, max(min(n_params, 1000) // 125, 1)
                              ).astype(jnp.float32),
            window=jnp.arange(5, 130, dtype=jnp.float32))
        sw = np.asarray(sgrid["window"])
        sb = np.asarray(sgrid["band"])

        def run_stoch():
            return fused.fused_stochastic_sweep(
                panel.close, panel.high, panel.low, sw, sb, cost=1e-3)

        rates["stochastic_fused"] = _measure(
            run_stoch, n_tickers * sweep.grid_size(sgrid), iters=iters,
            warmup=warmup, name="stochastic_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 12, np.unique(sw).size, sw.size,
                         prep_passes=4))

    # --- rsi / macd: the EMA-family fused kernels -------------------------
    if enabled("rsi_fused"):
        # 25 distinct periods (not 50): each distinct period unrolls an
        # associative EMA scan in the prep, and XLA compile time scales with
        # the count — the proxy backend cannot persistently cache compiles.
        rp = np.tile(np.arange(5, 55, 2, dtype=np.float32),
                     max(min(n_params, 1000) // 25, 1))
        rb = np.repeat(np.linspace(10, 30, max(min(n_params, 1000) // 25, 1)
                                   ).astype(np.float32), 25)

        def run_rsi():
            return fused.fused_rsi_sweep(panel.close, rp, rb, cost=1e-3)

        rates["rsi_fused"] = _measure(
            run_rsi, n_tickers * len(rp), iters=iters, warmup=warmup,
            name="rsi_fused", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 10, np.unique(rp).size, rp.size,
                         prep_passes=4))

    if enabled("macd_fused"):
        mf = np.repeat(np.arange(5, 15, dtype=np.float32), 100)
        ms = np.tile(np.repeat(np.arange(20, 60, 4, dtype=np.float32), 10),
                     10)
        mg = np.tile(np.arange(5, 15, dtype=np.float32), 100)

        def run_macd():
            return fused.fused_macd_sweep(panel.close, mf, ms, mg, cost=1e-3)

        rates["macd_fused"] = _measure(
            run_macd, n_tickers * len(mf), iters=iters, warmup=warmup,
            name="macd_fused", n_bars=n_bars,
            model=_model(TAIL + 5 * rounds + 5,
                         np.unique(np.r_[mf, ms]).size, mf.size,
                         prep_passes=4))

    if enabled("trix_fused"):
        # 10 distinct spans x 100 signal lanes; each distinct span chains
        # THREE EMA ladders in the prep (triple smoothing), hence the
        # heavier prep_passes.
        tsp = np.repeat(np.arange(5, 15, dtype=np.float32), 100)
        tsg = np.tile(np.arange(3, 13, dtype=np.float32), 100)

        def run_trix():
            return fused.fused_trix_sweep(panel.close, tsp, tsg, cost=1e-3)

        rates["trix_fused"] = _measure(
            run_trix, n_tickers * len(tsp), iters=iters, warmup=warmup,
            name="trix_fused", n_bars=n_bars,
            model=_model(TAIL + 5 * rounds + 7, np.unique(tsp).size,
                         tsp.size, prep_passes=10))

    if enabled("obv_fused"):
        ow = np.tile(np.arange(5, 130, dtype=np.float32),
                     max(n_params // 125, 1))

        def run_obv():
            return fused.fused_obv_sweep(panel.close, panel.volume, ow,
                                         cost=1e-3)

        # Default substrate is the in-kernel SMA-of-OBV table (VMEM
        # scratch, `_obv_kernel_inline`; measured 23.9 -> 25.3 M/s over
        # the W-major XLA table): the obv/returns/cs rows are the only
        # HBM streams, the VPU term gains the amortized table build.
        obv_inline = os.environ.get("DBX_OBV_TABLE", "inline") == "inline"
        obv_model = _model(TAIL + 8, np.unique(ow).size, ow.size,
                           prep_passes=0 if obv_inline else 2)
        if obv_inline:
            obv_p_pad = -(-ow.size // 128) * 128
            obv_model["hbm"] = 4.0 * 3 / obv_p_pad
            obv_model["vpu"] += 4.0 * np.unique(ow).size * 8 / obv_p_pad
        rates["obv_fused"] = _measure(
            run_obv, n_tickers * len(ow), iters=iters, warmup=warmup,
            name="obv_fused", n_bars=n_bars,
            model=obv_model)

    # --- configs[3]: rolling-OLS pairs (lookback, z_entry) ----------------
    if enabled("pairs"):
        n_pairs = min(2 * n_tickers, 1000)
        pair_data = data.synthetic_ohlcv(2 * n_pairs, n_bars, seed=1)
        closes = jax.device_put(jnp.asarray(pair_data.close), dev)
        y_close, x_close = closes[:n_pairs], closes[n_pairs:]
        pgrid = sweep.product_grid(
            lookback=jnp.arange(20, 70, 5, dtype=jnp.float32),
            z_entry=jnp.linspace(0.5, 3.0, 50).astype(jnp.float32))
        plb = np.asarray(pgrid["lookback"])
        pze = np.asarray(pgrid["z_entry"])

        if os.environ.get("DBX_BENCH_GENERIC") == "1":
            def run_pairs():
                return pairs.chunked_pairs_sweep(
                    y_close, x_close, pgrid, param_chunk=50, cost=1e-3)
        else:
            def run_pairs():
                return fused.fused_pairs_sweep(
                    y_close, x_close, plb, pze, cost=1e-3)

        rates["pairs"] = _measure(
            run_pairs, n_pairs * sweep.grid_size(pgrid),
            iters=max(iters // 2, 3), warmup=max(warmup // 3, 2),
            name="pairs", n_bars=n_bars,
            model=_model(TAIL + LADDER3 + 15, np.unique(plb).size,
                         plb.size, selections=2, prep_passes=8))

    # --- e2e: backtests/sec THROUGH the gRPC dispatch loop ----------------
    # The reference's one perf fact is jobs/sec through its full loop
    # (1 job/sec/worker: its compute slot sleeps 1 s per job, reference
    # src/worker/process.rs:23). This config measures the same thing
    # honestly for this framework: dispatcher + worker over loopback gRPC,
    # inline DBX1 payloads, decode + RPC + metric pack-and-report included.
    def run_e2e(name, *, top_k=0):
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu.rpc.compute import (
            JaxSweepBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            synthetic_jobs)
        from distributed_backtesting_exploration_tpu.rpc.worker import Worker

        e2e_iters = max(iters // 3, 2)
        n_jobs = n_tickers
        e2e_grid = {
            "fast": np.arange(5, 25, dtype=np.float32),
            "slow": np.arange(30, 30 + 2 * max(n_params // 20, 1), 2,
                              dtype=np.float32)}
        combos = int(np.prod([v.size for v in e2e_grid.values()]))
        topk_kw = (dict(top_k=top_k, rank_metric="sharpe") if top_k
                   else {})

        queue = JobQueue()
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=0.5).start()
            worker = Worker(f"localhost:{srv.port}", JaxSweepBackend(),
                            poll_interval_s=0.005, status_interval_s=0.5,
                            jobs_per_chip=100)
            wt = threading.Thread(target=worker.run, daemon=True)

            def drain(seed):
                for rec in synthetic_jobs(n_jobs, n_bars, "sma_crossover",
                                          e2e_grid, cost=1e-3, seed=seed,
                                          **topk_kw):
                    queue.enqueue(rec)
                deadline = time.monotonic() + 600.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit(f"bench[{name}]: drain wedged for 600s — "
                                 "backend failing every batch? "
                                 f"stats={queue.stats()}")
                    time.sleep(0.002)

            try:
                wt.start()
                t0 = time.perf_counter()
                drain(seed=100)          # compile + pipeline warm-up
                compile_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                for i in range(e2e_iters):
                    drain(seed=101 + i)
                elapsed = time.perf_counter() - t0
            finally:
                worker.stop()
                wt.join(timeout=30)
                srv.stop()
            rate = n_jobs * combos * e2e_iters / elapsed
            print(f"bench[{name}]: warmup {compile_s:.1f}s, {e2e_iters}x "
                  f"{n_jobs * combos} backtests through the dispatch loop "
                  f"in {elapsed:.3f}s -> {rate/1e6:.2f}M/s "
                  f"({worker.jobs_completed} jobs)", file=sys.stderr)
            rates[name] = rate

    if enabled("e2e"):
        run_e2e("e2e")
    # Same loop with on-device top-k reduction (JobSpec.top_k): workers
    # ship 16 rows instead of the full per-combo matrix, taking the d2h
    # result transfer and the completion leg off the critical path.
    if enabled("e2e_topk"):
        run_e2e("e2e_topk", top_k=16)

    # --- e2e_local: control-plane saturation (no TPU, no tunnel) ----------
    # `e2e` above is tunnel-bound on remote-proxy chips; this config
    # measures the DISPATCHER's own ceiling: N workers with an instant
    # compute backend drain a queue of small inline jobs over loopback
    # gRPC, so every second is framework control plane — RPC serving under
    # the GIL, queue state transitions (native core), completion batching.
    # Reported as JOBS/s per worker count; the 1->2->4 scaling curve (or
    # its absence) localizes the saturation point (DESIGN.md "Control-plane
    # ceiling"). The reference's one perf fact is jobs/s through its loop.
    def _worker_wire_bytes():
        """Sum of the workers' serialized request/reply proto bytes (the
        dbx_worker_wire_bytes_total counters, shared registry) — the
        instrument behind every wire_bytes_per_job column."""
        from distributed_backtesting_exploration_tpu import obs as obs_mod

        reg = obs_mod.get_registry()
        return sum(
            reg.counter("dbx_worker_wire_bytes_total",
                        method=m, direction=d).value
            for m in ("RequestJobs", "CompleteJobs", "FetchPayload")
            for d in ("request", "reply"))

    def run_e2e_local(n_workers, n_jobs, *, job_recs=None, dedupe=True,
                      name=None):
        """The loopback control-plane drain. ``job_recs`` (a factory
        seed -> record list) overrides the default distinct-panel
        synthetic workload — the dedupe A/B passes a shared-panel
        factory; ``dedupe`` toggles dispatch-by-digest on the
        dispatcher. Returns (jobs/s, wire bytes/job)."""
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu.rpc.compute import (
            InstantBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            synthetic_jobs)
        from distributed_backtesting_exploration_tpu.rpc.worker import Worker

        lgrid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}
        if job_recs is None:
            def job_recs(n, seed):
                return synthetic_jobs(n, 32, "sma_crossover", lgrid,
                                      seed=seed)
        name = name or f"e2e_local_w{n_workers}"
        queue = JobQueue()
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir, panel_dedupe=dedupe)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=0.5).start()
            workers = [Worker(f"localhost:{srv.port}", InstantBackend(),
                              worker_id=f"local-{i}",
                              poll_interval_s=0.001, status_interval_s=0.5,
                              jobs_per_chip=32)
                       for i in range(n_workers)]
            threads = [threading.Thread(target=w.run, daemon=True)
                       for w in workers]

            def drain(n, seed):
                for rec in job_recs(n, seed):
                    queue.enqueue(rec)
                deadline = time.monotonic() + 300.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit(f"bench[e2e_local]: drain wedged for 300s "
                                 f"— stats={queue.stats()}")
                    time.sleep(0.002)

            try:
                for t in threads:
                    t.start()
                drain(max(n_jobs // 4, 64), seed=300)   # channel warm-up
                wire0 = _worker_wire_bytes()
                t0 = time.perf_counter()
                drain(n_jobs, seed=301)
                elapsed = time.perf_counter() - t0
                wire_per_job = (_worker_wire_bytes() - wire0) / n_jobs
            finally:
                for w in workers:
                    w.stop()
                for t in threads:
                    t.join(timeout=30)
                srv.stop()
        rate = n_jobs / elapsed
        print(f"bench[{name}]: {n_jobs} instant jobs, "
              f"{n_workers} worker(s), substrate={queue.substrate}, "
              f"dedupe={'on' if dedupe else 'off'} -> {rate:.0f} jobs/s, "
              f"{wire_per_job:.0f} wire B/job", file=sys.stderr)
        rates[name] = rate
        return rate, wire_per_job

    if enabled("e2e_local"):
        n_local_jobs = int(os.environ.get("DBX_BENCH_LOCAL_JOBS", 1500))
        wcounts = tuple(int(x) for x in os.environ.get(
            "DBX_BENCH_LOCAL_WORKERS", "1,2,4").split(","))
        wire_cols = {}
        for n_workers in wcounts:
            _, wb = run_e2e_local(n_workers, n_local_jobs)
            wire_cols[f"w{n_workers}"] = round(wb, 1)
        # Dispatch-by-digest A/B on the workload the feature exists for:
        # many jobs sharing ONE panel (a grid sweep re-ships the same
        # OHLC bytes in every job). Dedupe-on ships the panel once per
        # worker and digest-only afterwards — the jobs/s delta is exactly
        # the per-job payload marshalling the control-plane ceiling
        # measured as its floor.
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            JobRecord)
        from distributed_backtesting_exploration_tpu.utils import (
            data as dd_data)

        dd_bars = int(os.environ.get("DBX_BENCH_DEDUPE_BARS", 4096))
        dd_jobs_n = max(n_local_jobs // 2, 48)
        dd_series = dd_data.synthetic_ohlcv(1, dd_bars, seed=500)
        dd_blob = dd_data.to_wire_bytes(
            type(dd_series)(*(np.asarray(f[0]) for f in dd_series)))
        dd_grid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}

        def dd_recs(n, seed):
            return [JobRecord(id=f"dd-{seed}-{i}",
                              strategy="sma_crossover", grid=dd_grid,
                              ohlcv=dd_blob) for i in range(n)]

        r_on, wb_on = run_e2e_local(1, dd_jobs_n, job_recs=dd_recs,
                                    dedupe=True,
                                    name="e2e_local_dedupe_on")
        r_off, wb_off = run_e2e_local(1, dd_jobs_n, job_recs=dd_recs,
                                      dedupe=False,
                                      name="e2e_local_dedupe_off")
        ROOFLINE["e2e_local"] = {
            "wire_bytes_per_job": wire_cols,
            "dedupe": {
                "panel_bytes": len(dd_blob),
                "jobs": dd_jobs_n,
                "jobs_per_s_on": round(r_on, 1),
                "jobs_per_s_off": round(r_off, 1),
                "dedupe_speedup": round(r_on / max(r_off, 1e-9), 3),
                "wire_bytes_per_job_on": round(wb_on, 1),
                "wire_bytes_per_job_off": round(wb_off, 1),
                "wire_reduction": round(wb_off / max(wb_on, 1e-9), 1)}}

    # --- direct_dispatch: the dispatcher-attributable ceiling -------------
    # e2e_local_w* runs dispatcher AND workers as threads of ONE Python
    # process on this 1-core box, so its flat w1->w4 curve measures the
    # shared GIL/core, not dispatcher scaling (VERDICT r4 weak #5). This
    # instrument removes the worker loop entirely: a bare client cycle
    # (RequestJobs -> CompleteJobs) against the served dispatcher, so every
    # second is gRPC serving + queue state machine + per-job marshalling —
    # DESIGN.md "Control-plane ceiling"'s direct-dispatch rows, recorded in
    # BENCH JSON instead of prose.
    def run_direct_dispatch(batch, n_jobs):
        import tempfile

        import grpc

        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as pb, service)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            synthetic_jobs)

        lgrid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}
        queue = JobQueue()
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=5.0).start()
            channel = grpc.insecure_channel(
                f"localhost:{srv.port}",
                options=service.default_channel_options(),
                compression=grpc.Compression.Gzip)
            stub = service.DispatcherStub(channel)

            def cycle(n, seed):
                for rec in synthetic_jobs(n, 32, "sma_crossover", lgrid,
                                          seed=seed):
                    queue.enqueue(rec)
                done = 0
                wire = 0
                while done < n:
                    req = pb.JobsRequest(
                        worker_id="direct", chips=1, jobs_per_chip=batch)
                    reply = stub.RequestJobs(req)
                    if not reply.jobs:
                        break
                    wire += req.ByteSize() + reply.ByteSize()
                    creq = pb.CompleteBatch(
                        worker_id="direct",
                        items=[pb.CompleteItem(id=j.id, metrics=b"",
                                               elapsed_s=0.0)
                               for j in reply.jobs])
                    crep = stub.CompleteJobs(creq)
                    wire += creq.ByteSize() + crep.ByteSize()
                    done += len(reply.jobs)
                return done, wire

            try:
                cycle(max(n_jobs // 4, 64), seed=400)   # warm the channel
                t0 = time.perf_counter()
                done, wire = cycle(n_jobs, seed=401)
                elapsed = time.perf_counter() - t0
            finally:
                channel.close()
                srv.stop()
        rate = done / elapsed
        wire_per_job = wire / max(done, 1)
        name = f"direct_dispatch_b{batch}"
        print(f"bench[{name}]: {done} inline jobs, bare client cycle, "
              f"batch {batch}, substrate={queue.substrate} -> "
              f"{rate:.0f} jobs/s, {wire_per_job:.0f} wire B/job",
              file=sys.stderr)
        rates[name] = rate
        return rate, wire_per_job

    if enabled("direct_dispatch"):
        dd_jobs = int(os.environ.get("DBX_BENCH_LOCAL_JOBS", 1500))
        r32, wb32 = run_direct_dispatch(32, dd_jobs)
        _, wb128 = run_direct_dispatch(128, dd_jobs)
        # Regression floor: DESIGN.md measured ~5.9k jobs/s at batch 32 on
        # this 1-core box; 2k leaves 3x headroom for a loaded machine
        # while still catching an order-of-magnitude regression.
        if r32 < 2000:
            print(f"bench[direct_dispatch]: WARNING batch-32 ceiling "
                  f"{r32:.0f} jobs/s is below the 2k regression floor "
                  "(DESIGN.md measured ~5.9k)", file=sys.stderr)
        ROOFLINE["direct_dispatch_floor"] = {
            "batch32_jobs_per_s": round(r32, 1), "floor": 2000,
            "floor_ok": bool(r32 >= 2000),
            "wire_bytes_per_job": {"b32": round(wb32, 1),
                                   "b128": round(wb128, 1)}}

        # Lockdep A/B: the same batch-32 cycle with the runtime lock
        # sanitizer (analysis.lockdep) instrumenting every package lock
        # — its overhead is a tracked number, and the shim must hold
        # the same 2k floor so DBX_LOCKDEP=1 is viable on live fleets.
        # Queue/dispatcher are constructed INSIDE run_direct_dispatch,
        # after install, so the hot-path locks are really wrapped.
        from distributed_backtesting_exploration_tpu.analysis import (
            lockdep)

        # Restore the PRIOR state afterwards: an in-process caller (the
        # roofline test fixture, a DBX_LOCKDEP=1 harness run) must keep
        # its shim AND its accumulated tables — a pre-existing violation
        # must survive the bench, so an already-active harness is never
        # reset; this block then reports the run's DELTA. (Under an
        # already-active shim the "off" baseline above was itself
        # instrumented, so overhead_pct reads ~0 there — the tracked
        # number comes from the normal uninstrumented bench run.)
        was_active = lockdep.active()
        if was_active:
            base = lockdep.report()
            base_edges, base_viol = base["edges"], len(base["violations"])
        else:
            lockdep.install()
            lockdep.reset()
            base_edges = base_viol = 0
        try:
            r32_ld, _ = run_direct_dispatch(32, dd_jobs)
            ld = lockdep.report()
        finally:
            if not was_active:
                lockdep.uninstall()
        edges = ld["edges"] - base_edges
        violations = len(ld["violations"]) - base_viol
        print(f"bench[direct_dispatch]: lockdep on -> {r32_ld:.0f} jobs/s "
              f"({(r32 - r32_ld) / max(r32, 1e-9) * 100:+.1f}% vs off), "
              f"{edges} edges, {violations} violations",
              file=sys.stderr)
        ROOFLINE["direct_dispatch_floor"]["lockdep"] = {
            "batch32_jobs_per_s": round(r32_ld, 1),
            "overhead_pct": round((r32 - r32_ld) / max(r32, 1e-9) * 100,
                                  1),
            "floor_ok": bool(r32_ld >= 2000),
            "edges": edges,
            "violations": violations}

    # --- fleet_telemetry: gossip overhead + staleness (round 15) ----------
    # Two instruments. (a) The direct_dispatch floor re-measured with a
    # telemetry frame built and attached per poll (obs/fleet.py
    # WorkerTelemetry -> JobsRequest.telemetry_json -> FleetView merge):
    # the frame build + dispatcher merge are the ONLY delta vs the off
    # arm, so the jobs/s gap IS the gossip's control-plane cost — the
    # acceptance bar says <= 5% with the 2k floor holding. (b) A tiny
    # real-worker loopback fleet (instant backend) drained while the
    # FleetView is sampled: every live worker must appear in /fleet.json
    # with frame staleness within 2 poll periods (fleet_staleness_p95_s).
    def run_fleet_direct(batch, n_jobs, telemetry):
        import tempfile

        import grpc

        from distributed_backtesting_exploration_tpu.obs import (
            fleet as fleet_mod)
        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as pb, service)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            synthetic_jobs)

        lgrid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}
        queue = JobQueue()
        counters = {"jobs": 0}
        telem = None
        if telemetry:
            telem = fleet_mod.WorkerTelemetry(
                "direct", stats_fn=lambda: {
                    "jobs_completed": counters["jobs"], "busy": 1})
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=5.0).start()
            channel = grpc.insecure_channel(
                f"localhost:{srv.port}",
                options=service.default_channel_options(),
                compression=grpc.Compression.Gzip)
            stub = service.DispatcherStub(channel)

            def cycle(n, seed):
                for rec in synthetic_jobs(n, 32, "sma_crossover", lgrid,
                                          seed=seed):
                    queue.enqueue(rec)
                done = 0
                while done < n:
                    req = pb.JobsRequest(
                        worker_id="direct", chips=1, jobs_per_chip=batch,
                        telemetry_json=(telem.take_frame_json()
                                        if telem is not None else ""))
                    reply = stub.RequestJobs(req)
                    if not reply.jobs:
                        break
                    stub.CompleteJobs(pb.CompleteBatch(
                        worker_id="direct",
                        items=[pb.CompleteItem(id=j.id, metrics=b"",
                                               elapsed_s=0.0)
                               for j in reply.jobs]))
                    done += len(reply.jobs)
                    counters["jobs"] += len(reply.jobs)
                return done

            try:
                cycle(max(n_jobs // 4, 64), seed=700)   # warm the channel
                t0 = time.perf_counter()
                done = cycle(n_jobs, seed=701)
                elapsed = time.perf_counter() - t0
                frames = disp.fleet.frame_sizes()
            finally:
                channel.close()
                srv.stop()
        return done / elapsed, frames

    def run_fleet_e2e(n_workers, n_jobs, poll_s):
        import tempfile
        import threading
        import urllib.request

        import grpc

        from distributed_backtesting_exploration_tpu.obs import (
            fleet as fleet_mod)
        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as pb, service)
        from distributed_backtesting_exploration_tpu.rpc.compute import (
            InstantBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            synthetic_jobs)
        from distributed_backtesting_exploration_tpu.rpc.worker import (
            Worker)

        lgrid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}
        queue = JobQueue()
        ages: list[float] = []
        # Straggler probes: two extra fleet members polling the REAL
        # RequestJobs leg whose frames carry their own execute-stage
        # streams — a healthy bulk and an artificially slowed worker.
        # The slow one must come out flagged in the merged view, and
        # the fleet execute histogram must fold their streams exactly.
        probe_stats = {}
        for wid, durs in (("fleet-fast", [0.001] * 100),
                          ("fleet-slow", [0.8] * 4)):
            st = fleet_mod._StageStats()
            for d in durs:
                st.observe({"name": "worker.execute", "dur_s": d})
            probe_stats[wid] = st
        stop_probes = threading.Event()

        def probe_loop(wid, port):
            telem = fleet_mod.WorkerTelemetry(
                wid, stats_fn=lambda: {"busy": 0},
                stages=probe_stats[wid])
            ch = grpc.insecure_channel(
                f"localhost:{port}",
                options=service.default_channel_options())
            stub = service.DispatcherStub(ch)
            try:
                while not stop_probes.is_set():
                    try:
                        reply = stub.RequestJobs(pb.JobsRequest(
                            worker_id=wid, chips=1, jobs_per_chip=1,
                            telemetry_json=telem.take_frame_json()),
                            timeout=10.0)
                        if reply.jobs:
                            stub.CompleteJobs(pb.CompleteBatch(
                                worker_id=wid,
                                items=[pb.CompleteItem(
                                    id=j.id, metrics=b"", elapsed_s=0.0)
                                    for j in reply.jobs]), timeout=10.0)
                    except grpc.RpcError:
                        pass
                    stop_probes.wait(poll_s)
            finally:
                ch.close()

        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=0.5, metrics_port=0,
                                   metrics_host="127.0.0.1").start()
            workers = [Worker(f"localhost:{srv.port}", InstantBackend(),
                              worker_id=f"fleet-{i}",
                              poll_interval_s=poll_s,
                              status_interval_s=0.5, jobs_per_chip=16)
                       for i in range(n_workers)]
            threads = [threading.Thread(target=w.run, daemon=True)
                       for w in workers]
            threads += [threading.Thread(target=probe_loop,
                                         args=(wid, srv.port),
                                         daemon=True)
                        for wid in probe_stats]
            # Freshness contract under test: "staleness <= 2 poll
            # periods" holds for IDLE workers too only when the
            # heartbeat rides the poll cadence — the operator knob this
            # config pins. Set immediately before the try whose finally
            # restores it, so a constructor failure above cannot leak
            # the override into the rest of the process (worker/probe
            # threads read it lazily, after start()).
            prior_hb = os.environ.get("DBX_FLEET_HEARTBEAT_S")
            os.environ["DBX_FLEET_HEARTBEAT_S"] = str(poll_s)
            try:
                for t in threads:
                    t.start()
                for rec in synthetic_jobs(n_jobs, 32, "sma_crossover",
                                          lgrid, seed=702):
                    queue.enqueue(rec)
                deadline = time.monotonic() + 300.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit("bench[fleet_telemetry]: drain wedged "
                                 f"for 300s — stats={queue.stats()}")
                    # Sample the live view mid-drain: per-worker frame
                    # ages feed the staleness p95.
                    snap = disp.fleet.snapshot()
                    ages.extend(w["age_s"]
                                for w in snap["workers"].values())
                    time.sleep(0.01)
                # Let the probes' frames land even on a tiny drain.
                deadline = time.monotonic() + 10.0
                while (len(disp.fleet.snapshot()["workers"])
                       < n_workers + len(probe_stats)
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                # The served route, end to end (dbxtop's feed).
                url = (f"http://127.0.0.1:{srv.metrics.port}"
                       "/fleet.json")
                with urllib.request.urlopen(url, timeout=10) as resp:
                    doc = json.loads(resp.read())
                frames = disp.fleet.frame_sizes()
            finally:
                stop_probes.set()
                for w in workers:
                    w.stop()
                for t in threads:
                    t.join(timeout=30)
                srv.stop()
                if prior_hb is None:
                    os.environ.pop("DBX_FLEET_HEARTBEAT_S", None)
                else:
                    os.environ["DBX_FLEET_HEARTBEAT_S"] = prior_hb
        return doc, ages, frames

    if enabled("fleet_telemetry"):
        ft_jobs = int(os.environ.get("DBX_BENCH_LOCAL_JOBS", 1500))
        ft_e2e_jobs = int(os.environ.get("DBX_BENCH_FLEET_JOBS", 600))
        ft_workers = int(os.environ.get("DBX_BENCH_FLEET_WORKERS", 2))
        # The production default poll period: "staleness <= 2 poll
        # periods" is measured against the cadence a real fleet runs at
        # (the frame rate floor DBX_FLEET_FRAME_MIN_S sits inside it).
        ft_poll = float(os.environ.get("DBX_BENCH_FLEET_POLL_S", 0.25))
        # Interleaved best-of-3 per arm: this box's run-to-run jitter
        # (~±5%) is the same order as the overhead bar, so a single
        # off-then-on pair confounds drift with cost; the best of three
        # interleaved trials isolates the arm's floor (the microbench
        # puts the true per-poll cost at ~2 µs suppressed / ~90 µs per
        # built frame — ~1-2% at saturation).
        r_off, r_on, on_frames = 0.0, 0.0, []
        for _ in range(3):
            r, _ = run_fleet_direct(32, ft_jobs, telemetry=False)
            r_off = max(r_off, r)
            r, f = run_fleet_direct(32, ft_jobs, telemetry=True)
            if r > r_on:
                r_on, on_frames = r, f
        overhead_pct = (r_off - r_on) / max(r_off, 1e-9) * 100
        doc, ages, e2e_frames = run_fleet_e2e(ft_workers, ft_e2e_jobs,
                                              ft_poll)
        from distributed_backtesting_exploration_tpu.obs import (
            timeline as tl_mod)

        frames = sorted(e2e_frames or on_frames)
        frame_p50 = frames[len(frames) // 2] if frames else 0
        # Same p95 estimator as the tenant queue-wait instrument — one
        # quantile method across the report's keys.
        stale_p95 = tl_mod._quantile(sorted(ages), 0.95) if ages else 0.0
        stale_bar = 2 * ft_poll
        expected_ids = ({f"fleet-{i}" for i in range(ft_workers)}
                        | {"fleet-fast", "fleet-slow"})
        workers_seen = set(doc.get("workers", {}))
        # The artificially slowed probe must come out flagged in the
        # merged view (the live straggler rule), and the fleet execute
        # histogram must equal the deterministic fold of the per-worker
        # rows (own-scope streams summed; proc-scope streams once per
        # pid) — the merged-histogram exactness contract, re-checked on
        # the SERVED document.
        straggler_flagged = "execute" in doc["workers"].get(
            "fleet-slow", {}).get("stragglers", [])
        own_n, own_sum = 0, 0.0
        per_pid: dict = {}
        for w in doc["workers"].values():
            if w.get("stale"):
                continue
            st = w.get("stages", {}).get("execute",
                                         {"n": 0, "sum_s": 0.0})
            if w.get("scope") == "worker":
                own_n += st["n"]
                own_sum += st["sum_s"]
            else:
                cur = per_pid.get(w["pid"])
                if cur is None or st["n"] > cur[0]:
                    per_pid[w["pid"]] = (st["n"], st["sum_s"])
        exp_n = own_n + sum(n for n, _ in per_pid.values())
        exp_sum = own_sum + sum(s for _, s in per_pid.values())
        ex = doc["fleet"]["stages"]["execute"]
        merge_exact = (ex["n"] == exp_n
                       and abs(ex["sum_s"] - exp_sum) < 1e-6)
        rates["fleet_telemetry"] = r_on
        ROOFLINE["fleet_telemetry"] = {
            "jobs": ft_jobs, "batch": 32,
            "jobs_per_s_off": round(r_off, 1),
            "jobs_per_s_on": round(r_on, 1),
            "telemetry_overhead_pct": round(overhead_pct, 1),
            "overhead_ok": bool(overhead_pct <= 5.0),
            "floor_ok": bool(r_on >= 2000),
            "frame_bytes_p50": frame_p50,
            "frames_sampled": len(frames),
            "e2e_jobs": ft_e2e_jobs, "e2e_workers": ft_workers,
            "e2e_poll_s": ft_poll,
            "workers_seen": len(workers_seen),
            "all_workers_visible": bool(expected_ids <= workers_seen),
            "fleet_staleness_p95_s": round(stale_p95, 4),
            "staleness_bar_s": round(stale_bar, 4),
            "staleness_ok": bool(stale_p95 <= stale_bar),
            "straggler_flagged": bool(straggler_flagged),
            "histogram_merge_exact": bool(merge_exact),
        }
        print(f"bench[fleet_telemetry]: direct b32 off {r_off:.0f} -> on "
              f"{r_on:.0f} jobs/s ({overhead_pct:+.1f}%), frame p50 "
              f"{frame_p50} B; e2e {ft_workers}+2 workers @ poll "
              f"{ft_poll * 1e3:.0f}ms -> {len(workers_seen)} visible, "
              f"staleness p95 {stale_p95 * 1e3:.0f}ms "
              f"(bar {stale_bar * 1e3:.0f}ms), straggler "
              f"{'flagged' if straggler_flagged else 'NOT FLAGGED'}, "
              f"merge {'exact' if merge_exact else 'MISMATCH'}",
              file=sys.stderr)

    # --- flight: recorder-armed overhead + residual plane (round 17) ------
    # Two instruments. (a) The direct_dispatch floor re-measured with the
    # flight recorder ARMED (DBX_FLIGHT_DIR set): the hot path never
    # builds a bundle — trigger() is a counter bump plus a dedupe-map
    # probe, and the happy path fires no trigger at all — so the
    # acceptance bar is <= 2% overhead with the 2k floor holding and
    # ZERO bundles written during the run; a capture_now smoke afterwards
    # proves the armed recorder really writes. (b) A deterministic
    # synthetic residual stream through CostModelTracker (durations are
    # computed FROM the op model — no wall clock), so the drift plane's
    # math — calibration warmup, signed EWMA, exact-fold histogram,
    # rank-interpolated quantiles — lands in BENCH JSON with a known
    # answer (costmodel_residual_{p50,p95}).
    if enabled("flight"):
        import tempfile

        from distributed_backtesting_exploration_tpu.obs import (
            costmodel as cm_mod, flight as flight_mod)
        from distributed_backtesting_exploration_tpu.obs.registry import (
            Registry)

        fl_jobs = int(os.environ.get("DBX_BENCH_LOCAL_JOBS", 1500))
        prior_fdir = os.environ.pop("DBX_FLIGHT_DIR", None)
        r_off = r_on = 0.0
        bundles_during = -1
        capture_ok = False
        try:
            with tempfile.TemporaryDirectory() as fdir:
                # Interleaved best-of-3 per arm (the fleet_telemetry
                # jitter argument: run-to-run drift on this box is the
                # same order as the overhead bar).
                for _ in range(3):
                    os.environ.pop("DBX_FLIGHT_DIR", None)
                    flight_mod.reset()
                    r, _ = run_direct_dispatch(32, fl_jobs)
                    r_off = max(r_off, r)
                    os.environ["DBX_FLIGHT_DIR"] = fdir
                    flight_mod.reset()
                    r, _ = run_direct_dispatch(32, fl_jobs)
                    r_on = max(r_on, r)
                bundles_during = len(
                    [f for f in os.listdir(fdir) if f.endswith(".json")])
                capture_ok = flight_mod.capture_now(
                    "admin", subject="bench-smoke") is not None
        finally:
            flight_mod.reset()
            if prior_fdir is None:
                os.environ.pop("DBX_FLIGHT_DIR", None)
            else:
                os.environ["DBX_FLIGHT_DIR"] = prior_fdir
        overhead_pct = (r_off - r_on) / max(r_off, 1e-9) * 100

        # (b) Synthetic residual stream: calibrate a private tracker at a
        # constant seconds-per-unit, then feed durations the model
        # predicts times 2**r for a fixed drift set — one guaranteed
        # blowout (first scored obs, before the calibration can absorb
        # anything), a +2 tail, a +0.5 body, a near-zero floor.
        tr = cm_mod.CostModelTracker(registry=Registry())
        spu0 = 1e-6
        base = {"name": "worker.execute",
                "kernel": "fused:sma_crossover",
                "bars": 2048, "combos": 64, "jobs": 1}
        units = cm_mod._model_units("sma_crossover", 2048, 64)

        def feed(r_log2):
            tr.observe(dict(base, dur_s=units * spu0 * (2.0 ** r_log2)))

        feed(0.0)                   # seeds the calibration at spu0
        for _ in range(cm_mod.warmup_n() - 1):
            feed(0.0)               # finish warmup; EWMA stays at spu0
        for r_log2 in [3.5] + [0.1] * 8 + [0.5] * 8 + [2.0] * 3:
            feed(r_log2)
        cm_snap = tr.snapshot()
        res_p50 = cm_mod.residual_quantile(cm_snap["buckets"], 0.5)
        res_p95 = cm_mod.residual_quantile(cm_snap["buckets"], 0.95)

        rates["flight"] = r_on
        ROOFLINE["flight"] = {
            "jobs": fl_jobs, "batch": 32,
            "jobs_per_s_off": round(r_off, 1),
            "jobs_per_s_on": round(r_on, 1),
            "overhead_pct": round(overhead_pct, 1),
            "overhead_ok": bool(overhead_pct <= 2.0),
            "floor_ok": bool(r_on >= 2000),
            "bundles_during_run": bundles_during,
            "quiet_ok": bool(bundles_during == 0),
            "capture_smoke_ok": bool(capture_ok),
            "costmodel_obs": cm_snap["n"],
            "costmodel_blowouts": cm_snap["blowouts"],
            "costmodel_residual_p50": round(res_p50, 4),
            "costmodel_residual_p95": round(res_p95, 4),
        }
        print(f"bench[flight]: direct b32 off {r_off:.0f} -> armed "
              f"{r_on:.0f} jobs/s ({overhead_pct:+.1f}%), "
              f"{bundles_during} bundle(s) during run, capture smoke "
              f"{'ok' if capture_ok else 'FAILED'}; synthetic residuals "
              f"p50 {res_p50:+.2f} / p95 {res_p95:+.2f} log2, "
              f"{cm_snap['blowouts']} blowout(s)", file=sys.stderr)

    # --- decision_plane: recorder-armed overhead + shadow scorer (r19) ----
    # Two instruments, the flight config's shape verbatim. (a) The
    # direct_dispatch floor re-measured with the decision plane recording
    # (DBX_DECISIONS on, the default) vs killed (=0): the hot path only
    # builds one small dict per dispatched job and deque-appends the
    # batch — scoring runs on the plane's own thread — so the acceptance
    # bar is <= 2% overhead with the 2k floor holding. Measurement:
    # five PAIRED rounds (killed then armed, back to back) and the
    # MEDIAN of the per-round deltas — this box's run-to-run swing
    # (±35%, DESIGN.md) is an order past the bar being measured, and
    # independent best-of-N arms inherit all of it; pairing cancels the
    # minutes-scale drift and the median rejects the symmetric
    # remainder. (b) A
    # deterministic synthetic decision stream through a private
    # DecisionPlane over a two-worker fleet (one holding the panel in
    # its top-K sketch, one not), placements split 12 resident / 4 not:
    # regret and agreement land in BENCH JSON with a known answer
    # (agreement 75%, regret = payload bytes over the nominal h2d rate
    # for every mis-placed decision).
    if enabled("decision_plane"):
        from distributed_backtesting_exploration_tpu.obs import (
            decisions as dec_mod)
        from distributed_backtesting_exploration_tpu.obs.registry import (
            Registry)

        dp_jobs = int(os.environ.get("DBX_BENCH_LOCAL_JOBS", 1500))
        prior_dec = os.environ.get("DBX_DECISIONS")
        r_off = r_on = 0.0
        dp_deltas = []
        try:
            for _ in range(5):
                os.environ["DBX_DECISIONS"] = "0"
                ro, _ = run_direct_dispatch(32, dp_jobs)
                os.environ["DBX_DECISIONS"] = "1"
                rn, _ = run_direct_dispatch(32, dp_jobs)
                r_off = max(r_off, ro)
                r_on = max(r_on, rn)
                dp_deltas.append((ro - rn) / max(ro, 1e-9) * 100)
        finally:
            if prior_dec is None:
                os.environ.pop("DBX_DECISIONS", None)
            else:
                os.environ["DBX_DECISIONS"] = prior_dec
        overhead_pct = sorted(dp_deltas)[len(dp_deltas) // 2]

        # (b) Synthetic shadow-score stream with a known answer.
        dp_digest = "ab" * 32
        dp_panel_b = 100_000_000

        class _DpFleet:
            def snapshot(self):
                return {"workers": {
                    "fast": {"stale": False, "age_s": 0.1,
                             "caches": {"panel_topk": [
                                 {"d": dp_digest[:12], "b": 1}]}},
                    "slow": {"stale": False, "age_s": 0.1,
                             "caches": {}}}}

        plane = dec_mod.DecisionPlane(fleet=_DpFleet(),
                                      registry=Registry())
        try:
            placements = ["fast"] * 12 + ["slow"] * 4
            plane.submit([
                {"jid": f"dp-{i}", "trace_id": f"dp-{i}", "worker": wid,
                 "tenant": "default", "strategy": "sma_crossover",
                 "combos": 64.0, "affinity_skips": 0, "wfq": None,
                 "digest": dp_digest, "panel_b": dp_panel_b,
                 "append_parent": "", "base_len": 0, "bars": 2048,
                 "t_take": float(i), "route": "full"}
                for i, wid in enumerate(placements)])
            scored = plane.flush(timeout=30.0)
            dp_snap = plane.snapshot()
        finally:
            plane.close()
        want_regret = dp_panel_b / dec_mod.h2d_rate_bps()

        rates["decision_plane"] = r_on
        ROOFLINE["decision_plane"] = {
            "jobs": dp_jobs, "batch": 32,
            "jobs_per_s_off": round(r_off, 1),
            "jobs_per_s_on": round(r_on, 1),
            "decision_overhead_delta_pct": round(overhead_pct, 1),
            "overhead_rounds_pct": [round(d, 1) for d in dp_deltas],
            "overhead_ok": bool(overhead_pct <= 2.0),
            "floor_ok": bool(r_on >= 2000),
            "shadow_scored": dp_snap["n_scored"] if scored else -1,
            "shadow_agreement_pct": dp_snap["agreement"]["pct"],
            "regret_p50": dp_snap["regret"]["p50_s"],
            "regret_p95": dp_snap["regret"]["p95_s"],
            "regret_expected_s": round(want_regret, 4),
        }
        print(f"bench[decision_plane]: direct b32 killed {r_off:.0f} -> "
              f"recording {r_on:.0f} jobs/s (median paired delta "
              f"{overhead_pct:+.1f}%); "
              f"shadow stream {dp_snap['n_scored']} scored, agreement "
              f"{dp_snap['agreement']['pct']:.0f}%, regret p50 "
              f"{dp_snap['regret']['p50_s']:.4f}s / p95 "
              f"{dp_snap['regret']['p95_s']:.4f}s (expected "
              f"{want_regret:.4f}s per mis-placement)", file=sys.stderr)

    # --- e2e_local_placement: locality-scored placement A/B (round 20) ----
    # The live placement stage's acceptance instrument: the SAME mixed
    # append-chain / paged-repeat / cold workload drained twice through
    # the loopback control plane — locality-blind (DBX_PLACEMENT=0, the
    # round-19 pure-WFQ path) vs placement-live — against a backend that
    # charges the simulated stage ladder keyed on what each worker
    # actually holds: a carry-store hit prices PL_CARRY_S, a full
    # reprice PL_REPRICE_S, and a panel miss adds PL_TRANSFER_S on top.
    # The dispatcher cannot cheat the sleeps — only routing jobs to the
    # worker holding the parent/panel avoids the expensive legs.
    # DBX_DECISIONS_H2D_GBPS is pinned so the op model's transfer term
    # matches the simulated link; the defer cap is raised because the
    # 2 ms poll loop burns a poll-scaled budget in milliseconds (the
    # production default assumes polls a batch-duration apart).
    # regret_seconds_{shadow,live} are the shadow scorer's measured
    # regret sums per arm: the live policy must leave strictly less on
    # the table than blind WFQ (regret_ok: live < shadow).
    if enabled("e2e_local_placement"):
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu import obs as obs_mod
        from distributed_backtesting_exploration_tpu.rpc import (
            panel_store as pl_store)
        from distributed_backtesting_exploration_tpu.rpc.compute import (
            Completion)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, JobRecord, PeerRegistry)
        from distributed_backtesting_exploration_tpu.rpc.worker import Worker
        from distributed_backtesting_exploration_tpu.utils import (
            data as pl_data)

        # Workload scale knobs: the tier-1 fixture shrinks the run to a
        # few seconds (structure test — the 1.5x bar belongs to the
        # real-size run, like the decision_plane bench discipline).
        pl_scale = float(os.environ.get("DBX_BENCH_PL_SCALE", 1.0))
        PL_REPRICE_S = 0.100 * pl_scale
        PL_CARRY_S = 0.002 * pl_scale
        PL_TRANSFER_S = 0.060 * pl_scale
        pl_bars, pl_step = 1024, 64
        pl_chains = int(os.environ.get("DBX_BENCH_PL_CHAINS", 10))
        pl_links = int(os.environ.get("DBX_BENCH_PL_LINKS", 20))
        pl_panels = 4
        pl_repeats = min(4, pl_links)
        pl_cold = min(8, pl_links)
        pl_grid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}

        class LocalityBackend:
            """Charges the stage ladder against what THIS worker holds:
            carry hit vs full reprice, resident panel vs h2d leg. Keys
            on digests only — digest-only dispatch never ships bytes it
            would not read anyway."""

            chips = 1

            def __init__(self):
                self.held: set[str] = set()

            def process(self, jobs):
                out = []
                for job in jobs:
                    base = job.append_parent_digest
                    if base and base in self.held:
                        dt = PL_CARRY_S
                    else:
                        dt = PL_REPRICE_S
                        if job.panel_digest not in self.held:
                            dt += PL_TRANSFER_S
                    time.sleep(dt)
                    self.held.add(job.panel_digest)
                    out.append(Completion(job.id, b"", dt,
                                          trace_id=job.trace_id))
                return out

        def pl_blob(seed, n):
            s = pl_data.synthetic_ohlcv(1, n, seed=seed)
            return pl_data.to_wire_bytes(
                type(s)(*(np.asarray(f[0][:n]) for f in s)))

        def pl_records():
            """The deterministic mixed workload, rebuilt per arm (fresh
            JobRecord objects — deferral bookkeeping must start cold).
            Chains are real append streams: every link extends the
            PREVIOUS link, so carry state lives only where the previous
            link ran."""
            master = pl_data.synthetic_ohlcv(
                1, pl_bars + pl_links * pl_step, seed=700)
            chains = []
            for c in range(pl_chains):
                links, prev_d, prev_n = [], "", 0
                for k in range(pl_links):
                    n = pl_bars + k * pl_step
                    blob = pl_data.to_wire_bytes(type(master)(
                        *(np.asarray(f[0][:n]) + c for f in master)))
                    links.append(JobRecord(
                        id=f"pl-c{c}-l{k}", strategy="sma_crossover",
                        grid=pl_grid, ohlcv=blob,
                        append_parent=prev_d, append_base_len=prev_n))
                    prev_d, prev_n = pl_store.panel_digest(blob), n
                chains.append(links)
            repeat_blobs = [pl_blob(710 + p, pl_bars)
                            for p in range(pl_panels)]
            cold_blobs = [pl_blob(730 + i, pl_bars) for i in range(pl_cold)]
            recs = []
            for r in range(pl_links):
                for links in chains:
                    recs.append(links[r])
                for p, blob in enumerate(repeat_blobs):
                    if r < pl_repeats:
                        recs.append(JobRecord(
                            id=f"pl-r{p}-{r}", strategy="sma_crossover",
                            grid=pl_grid, ohlcv=blob))
                if r < pl_cold:
                    recs.append(JobRecord(
                        id=f"pl-x{r}", strategy="sma_crossover",
                        grid=pl_grid, ohlcv=cold_blobs[r]))
            return recs

        def run_placement_arm(tag, live):
            env = {"DBX_PLACEMENT": "1" if live else "0",
                   "DBX_PLACEMENT_DEFER_CAP": "64",
                   "DBX_DECISIONS_H2D_GBPS": "0.0007",
                   "DBX_DECISIONS_RATE": "100000"}
            prior = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            reg = obs_mod.get_registry()
            counts0 = {o: reg.counter("dbx_placement_total", outcome=o).value
                       for o in ("served", "deferred", "cap")}
            queue = JobQueue()
            try:
                with tempfile.TemporaryDirectory() as results_dir:
                    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                                      results_dir=results_dir,
                                      panel_dedupe=True)
                    srv = DispatcherServer(disp, bind="localhost:0",
                                           prune_interval_s=0.5).start()
                    workers = [Worker(f"localhost:{srv.port}",
                                      LocalityBackend(),
                                      worker_id=f"pl-{i}",
                                      poll_interval_s=0.002,
                                      status_interval_s=0.5,
                                      jobs_per_chip=2)
                               for i in range(2)]
                    threads = [threading.Thread(target=w.run, daemon=True)
                               for w in workers]
                    try:
                        for t in threads:
                            t.start()
                        recs = pl_records()
                        for rec in recs:
                            queue.enqueue(rec)
                        t0 = time.perf_counter()
                        deadline = time.monotonic() + 300.0
                        while not queue.drained:
                            if time.monotonic() > deadline:
                                sys.exit(f"bench[e2e_local_placement/{tag}]: "
                                         f"drain wedged for 300s — "
                                         f"stats={queue.stats()}")
                            time.sleep(0.002)
                        elapsed = time.perf_counter() - t0
                        disp.decisions.flush(timeout=30.0)
                        snap = disp.decisions.snapshot()
                    finally:
                        for w in workers:
                            w.stop()
                        for t in threads:
                            t.join(timeout=30)
                        srv.stop()
            finally:
                for k, v in prior.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            counts = {o: reg.counter("dbx_placement_total", outcome=o).value
                      - counts0[o] for o in ("served", "deferred", "cap")}
            rate = len(recs) / elapsed
            print(f"bench[e2e_local_placement/{tag}]: {len(recs)} jobs, "
                  f"2 workers -> {rate:.0f} jobs/s, regret sum "
                  f"{snap['regret']['sum_s']:.3f}s over "
                  f"{snap['n_scored']} scored, placement counts "
                  f"{counts}", file=sys.stderr)
            return rate, snap, counts, len(recs)

        r_blind, snap_blind, _, _ = run_placement_arm("blind", live=False)
        r_live, snap_live, pl_counts, pl_n = run_placement_arm(
            "live", live=True)
        pl_polls = sum(pl_counts.values())
        pl_speedup = r_live / max(r_blind, 1e-9)
        regret_shadow = snap_blind["regret"]["sum_s"]
        regret_live = snap_live["regret"]["sum_s"]

        rates["e2e_local_placement"] = r_live
        ROOFLINE["e2e_local_placement"] = {
            "jobs": pl_n, "workers": 2,
            "jobs_per_s_blind": round(r_blind, 1),
            "jobs_per_s_live": round(r_live, 1),
            "placement_speedup": round(pl_speedup, 3),
            "defer_rate": round(
                pl_counts["deferred"] / max(pl_polls, 1), 4),
            "admit_counts": {o: int(v) for o, v in pl_counts.items()},
            "regret_seconds_shadow": round(regret_shadow, 4),
            "regret_seconds_live": round(regret_live, 4),
            "scored_shadow": snap_blind["n_scored"],
            "scored_live": snap_live["n_scored"],
            "speedup_ok": bool(pl_speedup >= 1.5),
            "regret_ok": bool(regret_live < regret_shadow),
        }
        print(f"bench[e2e_local_placement]: blind {r_blind:.0f} -> live "
              f"{r_live:.0f} jobs/s ({pl_speedup:.2f}x), regret "
              f"{regret_shadow:.3f}s -> {regret_live:.3f}s, defer rate "
              f"{pl_counts['deferred'] / max(pl_polls, 1):.3f}",
              file=sys.stderr)

    # --- queue_machine: the state machine alone, both substrates ----------
    # (VERDICT r4 weak #5 / next #7: the native DbxJobQueue driven per job
    # over ctypes measured ~2x SLOWER than the dict fallback; the batched
    # API — one crossing per take/complete batch — is the fix. This
    # microbench drives full lifecycle cycles, batch 32, through BOTH
    # substrates and records them side by side.)
    def run_queue_machine(substrate):
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            JobQueue, JobRecord)
        from distributed_backtesting_exploration_tpu.runtime import (
            _core as native_core)

        if substrate == "native" and not native_core.available():
            print("bench[queue_machine]: native core unavailable, skipping",
                  file=sys.stderr)
            return
        n_q_jobs = int(os.environ.get("DBX_BENCH_QUEUE_JOBS", 20000))
        recs = [JobRecord(id=f"q{i}", strategy="s", grid={}, ohlcv=b"x")
                for i in range(n_q_jobs)]
        best = 0.0
        for _ in range(3):   # best-of-3: this box's load varies ~50%
            q = JobQueue(use_native=(substrate == "native"))
            assert q.substrate == substrate
            t0 = time.perf_counter()
            for i in range(0, n_q_jobs, 32):   # RPC-sized intake batches
                q.enqueue_many(recs[i:i + 32])
            while True:
                got = q.take(32, "w")
                if not got:
                    break
                q.complete_batch([r.id for r, _ in got], "w")
            elapsed = time.perf_counter() - t0
            assert q.drained and q.stats()["jobs_completed"] == n_q_jobs
            best = max(best, n_q_jobs / elapsed)
        print(f"bench[queue_machine_{substrate}]: {n_q_jobs} full "
              f"enqueue->take(32)->complete_batch cycles, best of 3 "
              f"-> {best / 1e3:.0f}k jobs/s", file=sys.stderr)
        rates[f"queue_machine_{substrate}"] = best

    if enabled("queue_machine"):
        run_queue_machine("python")
        run_queue_machine("native")
        # The C-ABI grain — a native shell driving DbxJobQueue with no
        # foreign-function crossing (its real habitat; the reason the
        # native machine exists even though the Python-driven default
        # substrate is python).
        bench_bin = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "cpp", "build", "dbx_core_bench")
        if os.path.exists(bench_bin):
            import re
            import subprocess
            try:
                out = subprocess.run([bench_bin, "200000"],
                                     capture_output=True, text=True,
                                     timeout=120)
                m = re.search(r"-> (\d+) jobs/s", out.stdout)
                if out.returncode == 0 and m:
                    rates["queue_machine_native_cabi"] = float(m.group(1))
                    print("bench[queue_machine_native_cabi]: "
                          + out.stdout.strip(), file=sys.stderr)
            except (OSError, subprocess.SubprocessError) as e:
                print(f"bench[queue_machine_native_cabi]: skipped ({e})",
                      file=sys.stderr)

    # --- streaming_append: O(ΔT) live-bar serving A/B ---------------------
    # ROADMAP item 1's acceptance instrument: the same appended ΔT-bar
    # slice priced two ways — (A) the recurrent form advancing a carry
    # checkpoint (streaming.recurrent.append_step, the AppendBars serving
    # path) vs (B) today's cost model, a full scan-form reprice of the
    # whole (T+ΔT)-bar panel. Both run in-process on fixed shapes with
    # the compile walls warmed out, so the ratio is pure steady-state
    # work; `append_speedup` is the >=50x acceptance number at the
    # headline T=8192 / ΔT=16 (knobs DBX_BENCH_STREAM_T / _DT). The wire
    # columns record what AppendBars ships (one DBX1 ΔT slice) vs what a
    # full re-dispatch would (the whole extended panel).
    if enabled("streaming_append"):
        from distributed_backtesting_exploration_tpu.streaming import (
            recurrent as stream_rc)

        s_T = int(os.environ.get("DBX_BENCH_STREAM_T", 8192))
        s_DT = int(os.environ.get("DBX_BENCH_STREAM_DT", 16))
        s_iters = max(min(iters, 10), 3)
        sgrid = {k: np.asarray(v) for k, v in sweep.product_grid(
            fast=np.arange(5.0, 13.0, dtype=np.float32),
            slow=np.arange(30.0, 46.0, 4.0, dtype=np.float32)).items()}
        s_combos = int(sgrid["fast"].size)
        s_close = np.asarray(data.synthetic_ohlcv(
            1, s_T + s_DT * (s_iters + 1), seed=77).close)

        carry0 = stream_rc.build_carry("sma_crossover",
                                       {"close": s_close[:, :s_T]}, sgrid)
        # Warm both forms: the A/B must time steady-state work, not jit.
        np.asarray(stream_rc.finalize(stream_rc.append_step(
            carry0, {"close": s_close[:, s_T:s_T + s_DT]})).sharpe)
        np.asarray(stream_rc.finalize(stream_rc.build_carry(
            "sma_crossover", {"close": s_close[:, :s_T + s_DT]},
            sgrid)).sharpe)

        t0 = time.perf_counter()
        c = carry0
        for i in range(s_iters):
            lo = s_T + i * s_DT
            c = stream_rc.append_step(
                c, {"close": s_close[:, lo:lo + s_DT]})
            np.asarray(stream_rc.finalize(c).sharpe)   # the served result
        t_append = (time.perf_counter() - t0) / s_iters

        # Full reprice at a FIXED (T+ΔT) length per update: same compiled
        # shape every iteration (a per-update growing length would time
        # recompiles, not work).
        t0 = time.perf_counter()
        for _ in range(s_iters):
            np.asarray(stream_rc.finalize(stream_rc.build_carry(
                "sma_crossover", {"close": s_close[:, :s_T + s_DT]},
                sgrid)).sharpe)
        t_full = (time.perf_counter() - t0) / s_iters

        wire_full = 8 + 4 * 5 * (s_T + s_DT)     # DBX1: magic+T+5 f32[T]
        wire_delta = 8 + 4 * 5 * s_DT
        speedup = t_full / max(t_append, 1e-9)
        ROOFLINE["streaming_append"] = {
            "bars_base": s_T, "delta_bars": s_DT, "updates": s_iters,
            "combos": s_combos,
            "append_s_per_update": round(t_append, 6),
            "full_reprice_s_per_update": round(t_full, 6),
            "append_speedup": round(speedup, 2),
            "wire_bytes_full": wire_full,
            "wire_bytes_delta": wire_delta,
            "wire_reduction": round(wire_full / wire_delta, 1)}
        rates["streaming_append"] = 1.0 / max(t_append, 1e-9)
        print(f"bench[streaming_append]: T={s_T} dT={s_DT} "
              f"P={s_combos}: append {t_append * 1e3:.2f} ms/update vs "
              f"full reprice {t_full * 1e3:.1f} ms -> {speedup:.1f}x "
              f"(wire {wire_full}B -> {wire_delta}B)", file=sys.stderr)

    # --- certify: dbxcert numerics-certifier analysis cost ----------------
    # The certifier (analysis.certify) is a CI-gate stage like lint and
    # proto-drift: its wall is tracked per family exactly like every
    # compute stage, so a registry/analysis growth that would blow the
    # tier-1 budget shows up in BENCH JSON first. certify_wall_s maps
    # family -> seconds to certify its 4 rows (2 epilogue substrates x
    # {build_carry, append_step}); "digest" covers the scenario-synth +
    # wire-splice digest cones. DBX_BENCH_CERTIFY_FAMILIES subsets the
    # registry for tiny runs.
    if enabled("certify"):
        from distributed_backtesting_exploration_tpu.analysis import (
            certify as dbxcert)

        fams_env = os.environ.get("DBX_BENCH_CERTIFY_FAMILIES")
        fams = ([f.strip() for f in fams_env.split(",") if f.strip()]
                if fams_env else None)
        t0 = time.perf_counter()
        certify_rows, certify_walls = dbxcert.timed_rows(families=fams)
        certify_total = time.perf_counter() - t0
        ROOFLINE["certify"] = {
            "certify_wall_s": {k: round(v, 4)
                               for k, v in certify_walls.items()},
            "rows": len(certify_rows),
            "wall_s_total": round(certify_total, 4)}
        rates["certify"] = len(certify_rows) / max(certify_total, 1e-9)
        print(f"bench[certify]: {len(certify_rows)} rows in "
              f"{certify_total:.2f}s "
              f"({len(certify_walls) - 1} families + digest cones)",
              file=sys.stderr)

    # --- modelcheck: dbxmc interleaving/crash-point explorer cost ---------
    # The model checker (analysis.modelcheck) is a CI-gate stage like
    # lint and certify: its schedule throughput rides BENCH JSON so a
    # queue-code or invariant-table growth that would blow the tier-1
    # budget shows up here first. schedules/crash_points are summed over
    # every available substrate (python + native when loadable);
    # DBX_BENCH_MC_SCHEDULES subsets the sweep for tiny runs.
    if enabled("modelcheck"):
        from distributed_backtesting_exploration_tpu.analysis import (
            modelcheck as dbxmc)

        mc_cfg = dbxmc.MCConfig(
            ops=int(os.environ.get("DBX_MC_OPS", "12")),
            seed=int(os.environ.get("DBX_MC_SEED", "0")),
            schedules=int(os.environ.get("DBX_BENCH_MC_SCHEDULES", "120")))
        mc_res = dbxmc.explore(mc_cfg, dbxmc.available_substrates())
        ROOFLINE["modelcheck"] = {
            "schedules": mc_res["schedules"],
            "crash_points": mc_res["crash_points"],
            "boundaries": mc_res["boundaries"],
            "violations": len(mc_res["violations"]),
            "wall_s": mc_res["wall_s"]}
        rates["modelcheck"] = (mc_res["schedules"]
                               / max(mc_res["wall_s"], 1e-9))
        print(f"bench[modelcheck]: {mc_res['schedules']} schedules, "
              f"{mc_res['crash_points']} crash points, "
              f"{len(mc_res['violations'])} violations in "
              f"{mc_res['wall_s']:.2f}s", file=sys.stderr)

    # --- fanout: live signal fan-out scaling (serve/, ROADMAP item 3) -----
    # The serving-cost contract measured end to end: N subscriptions over
    # M symbol chains (all sharing one param block per symbol -> M unique
    # streams), one tick-only AppendBars per symbol, an instant-backend
    # worker draining the advance jobs over loopback gRPC, and every
    # push delivered through real server-streaming Subscribe calls.
    # `advances_per_tick` MUST equal unique streams per chain (1 here) —
    # carry advances scale with streams, not subscribers — and
    # `pushes_per_advance` is the fan-out amplification (N/M). Tick-to-
    # push latency is client-measured (same host, same clock): recv wall
    # minus the PushUpdate's dispatcher tick stamp; the p99 bar is
    # bench-pinned at 2s on this box (loopback + instant compute — the
    # number is the SERVING tier's overhead, not kernel wall).
    if enabled("fanout"):
        import tempfile
        import threading

        import grpc as grpc_mod

        from distributed_backtesting_exploration_tpu import obs as obs_mod
        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as fan_pb, service as fan_service,
            wire as fan_wire)
        from distributed_backtesting_exploration_tpu.rpc.compute import (
            InstantBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, JobRecord,
            PeerRegistry)
        from distributed_backtesting_exploration_tpu.rpc.worker import (
            Worker)

        sub_n = int(os.environ.get("DBX_BENCH_SUB_N", 10000))
        n_symbols = int(os.environ.get("DBX_BENCH_SUB_SYMBOLS", 1000))
        n_conns = min(int(os.environ.get("DBX_BENCH_SUB_CONNS", 32)),
                      sub_n)
        fan_bars = 64
        fan_grid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}
        hist = data.synthetic_ohlcv(n_symbols, fan_bars + 1, seed=700)

        def sym_cut(i, lo, hi):
            return data.to_wire_bytes(
                type(hist)(*(np.asarray(f[i, lo:hi]) for f in hist)))

        base_recs = [JobRecord(id=f"fan-{i}", strategy="sma_crossover",
                               grid=fan_grid, ohlcv=sym_cut(i, 0, fan_bars))
                     for i in range(n_symbols)]

        class _FanCollector:
            """Drains one Subscribe stream; samples tick->recv wall."""

            def __init__(self, stub, request, expected):
                self.lat: list[float] = []
                self.expected = expected
                self._call = stub.Subscribe(request)
                self.thread = threading.Thread(target=self._drain,
                                               daemon=True)
                self.thread.start()

            def _drain(self):
                try:
                    for item in self._call:
                        if item.tick_unix:
                            self.lat.append(time.time() - item.tick_unix)
                        if len(self.lat) >= self.expected:
                            break
                except grpc_mod.RpcError:
                    pass

            def stop(self):
                self._call.cancel()
                self.thread.join(timeout=10)

        queue = JobQueue()
        reg = obs_mod.get_registry()
        adv0 = reg.counter("dbx_stream_advances_total").value
        drop0 = reg.counter("dbx_sub_pushes_total",
                            outcome="dropped").value
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=0.5,
                                   max_workers=n_conns + 16).start()
            worker = Worker(f"localhost:{srv.port}", InstantBackend(),
                            worker_id="fanout-worker",
                            poll_interval_s=0.001, status_interval_s=0.5,
                            jobs_per_chip=64)
            wt = threading.Thread(target=worker.run, daemon=True)
            channel = grpc_mod.insecure_channel(
                f"localhost:{srv.port}",
                options=fan_service.default_channel_options())
            stub = fan_service.DispatcherStub(channel)
            collectors = []
            try:
                wt.start()
                for rec in base_recs:
                    queue.enqueue(rec)
                deadline = time.monotonic() + 300.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit("bench[fanout]: base drain wedged — "
                                 f"stats={queue.stats()}")
                    time.sleep(0.005)
                # N subscriptions spread so each symbol's subscribers
                # land on DISTINCT connections (a connection naming the
                # same stream twice is deduped by design — one
                # membership, one push — so per-stream fan-out is
                # counted in connections). Symbol s's k-th subscriber
                # rides connection (s + k) % n_conns: with
                # subs-per-symbol <= n_conns they are all distinct.
                per_sym = sub_n // n_symbols
                if per_sym > n_conns:
                    sys.exit("bench[fanout]: DBX_BENCH_SUB_CONNS "
                             f"({n_conns}) < subscribers per symbol "
                             f"({per_sym}) — a connection would hold "
                             "duplicate interests in one stream, which "
                             "dedupes to one push")
                per_conn = [[] for _ in range(n_conns)]
                for j in range(sub_n):
                    s, k = divmod(j, per_sym) if per_sym else (j, 0)
                    s %= n_symbols
                    per_conn[(s + k) % n_conns].append(fan_pb.JobSpec(
                        strategy="sma_crossover",
                        panel_digest=base_recs[s].panel_digest,
                        grid=fan_wire.grid_to_proto(fan_grid),
                        periods_per_year=252))
                for c, interests in enumerate(per_conn):
                    collectors.append(_FanCollector(
                        stub, fan_pb.SubscribeRequest(
                            subscriber_id=f"fan-c{c}",
                            interests=interests),
                        expected=len(interests)))
                deadline = time.monotonic() + 120.0
                while disp.hub.stats()["interests"] < sub_n:
                    if time.monotonic() > deadline:
                        sys.exit("bench[fanout]: subscriptions never "
                                 f"registered — {disp.hub.stats()}")
                    time.sleep(0.01)
                t0 = time.perf_counter()
                for i, rec in enumerate(base_recs):
                    r = stub.AppendBars(fan_pb.AppendRequest(
                        worker_id="feed", panel_digest=rec.panel_digest,
                        base_len=fan_bars,
                        delta=sym_cut(i, fan_bars, fan_bars + 1),
                        job=fan_pb.JobSpec()))
                    if not r.ok:
                        sys.exit(f"bench[fanout]: tick {i} rejected: "
                                 f"{r.detail}")
                t_ticks = time.perf_counter() - t0
                deadline = time.monotonic() + 300.0
                while any(len(c.lat) < c.expected for c in collectors):
                    if time.monotonic() > deadline:
                        got = sum(len(c.lat) for c in collectors)
                        # Drop-and-count is legal under load; report
                        # what arrived rather than wedging (the keys
                        # below carry the drop counter).
                        print(f"bench[fanout]: {got}/{sub_n} pushes "
                              "after 300s (rest dropped or late)",
                              file=sys.stderr)
                        break
                    time.sleep(0.01)
                t_all = time.perf_counter() - t0
            finally:
                for c in collectors:
                    c.stop()
                worker.stop()
                wt.join(timeout=30)
                channel.close()
                srv.stop()
        lat = sorted(x for c in collectors for x in c.lat)
        advances = reg.counter("dbx_stream_advances_total").value - adv0
        dropped = reg.counter("dbx_sub_pushes_total",
                              outcome="dropped").value - drop0
        from distributed_backtesting_exploration_tpu.obs.timeline import (
            _quantile)

        p99 = _quantile(lat, 0.99)
        p99_bar_s = 2.0
        ROOFLINE["fanout"] = {
            "subscriptions": sub_n, "symbols": n_symbols,
            "connections": n_conns,
            "unique_streams": n_symbols,
            "ticks": n_symbols,
            "advances_total": int(advances),
            "advances_per_tick": round(advances / max(n_symbols, 1), 4),
            "advances_eq_streams": bool(advances == n_symbols),
            "pushes_delivered": len(lat),
            "pushes_dropped": int(dropped),
            "pushes_per_advance": round(len(lat) / max(advances, 1), 2),
            "tick_to_push_p50_s": round(_quantile(lat, 0.50), 6),
            "tick_to_push_p99_s": round(p99, 6),
            "p99_bar_s": p99_bar_s,
            "p99_ok": bool(p99 <= p99_bar_s),
            "tick_wall_s": round(t_ticks, 3),
            "drain_wall_s": round(t_all, 3)}
        rates["fanout"] = len(lat) / max(t_all, 1e-9)
        print(f"bench[fanout]: {sub_n} subs / {n_symbols} symbols on "
              f"{n_conns} conns: {advances} advances "
              f"({advances / max(n_symbols, 1):.2f}/tick, streams="
              f"{n_symbols}), {len(lat)} pushes "
              f"({len(lat) / max(advances, 1):.1f}/advance, "
              f"{dropped} dropped), tick->push p50 "
              f"{_quantile(lat, 0.5) * 1e3:.1f}ms p99 {p99 * 1e3:.1f}ms, "
              f"drain {t_all:.1f}s", file=sys.stderr)

    # --- e2e_local_tenants: 3-tenant adversarial fairness A/B -------------
    # ROADMAP item 5's acceptance instrument: a whale tenant's oversized
    # grid sweep (many jobs x many combos) must not blow up a small
    # tenant's p95 queue wait. Two loopback drains with the SAME small-
    # tenant workload — (solo) the two small tenants without the whale,
    # (contended) the whale's whole backlog enqueued AHEAD of them — and
    # per-tenant p95 queue_wait measured through the PR 4 timeline
    # profiler (per-job critical-path stage attribution over the span
    # ring), tenants keyed by job-id prefix. Under the WFQ pop the whale
    # only interleaves at its combo-weighted share, so the ratio stays
    # near 1; the pre-tenancy FIFO would make it backlog/backlog (~5x
    # at the default sizes).
    def run_tenant_pass(tag, tenant_jobs, *, jobs_per_chip=8):
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu.rpc.compute import (
            InstantBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry)
        from distributed_backtesting_exploration_tpu.rpc.worker import (
            Worker)

        queue = JobQueue()
        n_total = 0
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=0.5).start()
            worker = Worker(f"localhost:{srv.port}", InstantBackend(),
                            worker_id=f"tenant-bench-{tag}",
                            poll_interval_s=0.001, status_interval_s=0.5,
                            jobs_per_chip=jobs_per_chip)
            wt = threading.Thread(target=worker.run, daemon=True)
            try:
                wt.start()
                t0 = time.perf_counter()
                for recs in tenant_jobs:
                    for rec in recs:
                        queue.enqueue(rec)
                    n_total += len(recs)
                deadline = time.monotonic() + 600.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit(f"bench[e2e_local_tenants:{tag}]: drain "
                                 f"wedged for 600s — stats={queue.stats()}")
                    time.sleep(0.002)
                elapsed = time.perf_counter() - t0
            finally:
                worker.stop()
                wt.join(timeout=30)
                srv.stop()
        return n_total / elapsed

    if enabled("e2e_local_tenants"):
        from distributed_backtesting_exploration_tpu import obs as obs_mod
        from distributed_backtesting_exploration_tpu.obs import (
            timeline as tl_mod)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            JobRecord)
        from distributed_backtesting_exploration_tpu.utils import (
            data as t_data)

        n_small = int(os.environ.get("DBX_BENCH_TENANT_SMALL_JOBS", 64))
        n_whale = int(os.environ.get("DBX_BENCH_TENANT_WHALE_JOBS", 512))
        whale_combos = int(os.environ.get(
            "DBX_BENCH_TENANT_WHALE_COMBOS", 64))
        t_series = t_data.synthetic_ohlcv(1, 32, seed=910)
        t_blob = t_data.to_wire_bytes(
            type(t_series)(*(np.asarray(f[0]) for f in t_series)))
        small_grid = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}
        whale_grid = {"fast": np.arange(
            5.0, 5.0 + whale_combos, dtype=np.float32)}

        def tenant_recs(tag, tenant, n, grid):
            return [JobRecord(id=f"{tag}:{tenant}-{i}",
                              strategy="sma_crossover", grid=grid,
                              ohlcv=t_blob, tenant=tenant)
                    for i in range(n)]

        def tenant_p95(tag, tenant):
            tls = tl_mod.reconstruct(obs_mod.recent_spans())
            # Same torn-job discipline as timeline.summarize_spans: ring
            # eviction tears a job's queue_wait head span first, and a
            # torn timeline's queue_wait stage reads ~0 — keeping it
            # would silently deflate the fairness p95 at scaled-up
            # whale sizes.
            tls = {t: tl for t, tl in tls.items()
                   if any(s["name"] == "job.queue_wait"
                          for s in tl.spans)}
            per_job = (tl_mod.summarize(
                tls, min_straggler_jobs=1 << 30)["per_job"]
                if tls else [])
            waits = sorted(j["stages"]["queue_wait"] for j in per_job
                           if j["job"].startswith(f"{tag}:{tenant}-"))
            if not waits:
                # Honest-numbers policy: a fairness bar must never pass
                # on zero measurements (ring eviction at scaled-up whale
                # sizes tears the small tenants' spans FIRST).
                sys.exit(f"bench[e2e_local_tenants]: no surviving "
                         f"queue_wait timelines for {tag}:{tenant} — "
                         "span ring too small for this job count")
            return tl_mod._quantile(waits, 0.95), len(waits)

        r_solo = run_tenant_pass("solo", [
            tenant_recs("solo", "small_a", n_small, small_grid),
            tenant_recs("solo", "small_b", n_small, small_grid)])
        solo = {t: tenant_p95("solo", t) for t in ("small_a", "small_b")}
        p95_solo = max(v[0] for v in solo.values())
        r_cont = run_tenant_pass("cont", [
            # Adversarial order: the whale's WHOLE sweep lands first.
            tenant_recs("cont", "whale", n_whale, whale_grid),
            tenant_recs("cont", "small_a", n_small, small_grid),
            tenant_recs("cont", "small_b", n_small, small_grid)])
        cont = {t: tenant_p95("cont", t)
                for t in ("whale", "small_a", "small_b")}
        per_tenant = {t: round(v[0], 6) for t, v in cont.items()}
        p95_cont = max(per_tenant["small_a"], per_tenant["small_b"])
        ratio = p95_cont / max(p95_solo, 1e-9)
        ROOFLINE["e2e_local_tenants"] = {
            # Sample counts per p95 (no silent caps: the quantiles above
            # are only as good as the timelines that survived the ring).
            "tenant_queue_wait_samples": {
                **{f"solo_{t}": v[1] for t, v in solo.items()},
                **{f"contended_{t}": v[1] for t, v in cont.items()}},
            "small_jobs": n_small, "whale_jobs": n_whale,
            "small_combos_per_job": int(small_grid["fast"].size),
            "whale_combos_per_job": whale_combos,
            "tenant_p95_queue_wait_solo": round(p95_solo, 6),
            "tenant_p95_queue_wait_contended": round(p95_cont, 6),
            "fairness_ratio": round(ratio, 3),
            "fairness_ok": bool(ratio <= 2.0),
            "per_tenant_p95_contended": per_tenant,
            "jobs_per_s_solo": round(r_solo, 1),
            "jobs_per_s_contended": round(r_cont, 1)}
        rates["e2e_local_tenants"] = r_cont
        print(f"bench[e2e_local_tenants]: whale {n_whale}x{whale_combos} "
              f"combos vs 2x{n_small} small jobs: small p95 queue_wait "
              f"{p95_solo * 1e3:.1f}ms solo -> {p95_cont * 1e3:.1f}ms "
              f"contended = {ratio:.2f}x (bar: <= 2x)", file=sys.stderr)

    # --- scenario_sweep: digest-seeded synthetic-panel generation ---------
    # The scenario workload's two facts: (a) generator throughput — a
    # (digest, params) spec replaces shipping/storing a whole panel, so
    # the generation rate IS the workload's ingest ceiling; (b) the e2e
    # dispatcher path — scenario jobs materialize through the panel
    # store at first take and drain like ordinary content-addressed
    # jobs. Reproducibility (same spec -> same digest) is asserted here
    # too: it is the property that makes the spec a valid wire unit.
    if enabled("scenario_sweep"):
        import dataclasses as dc
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu import (
            scenarios as scn_mod)
        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as s_pb)
        from distributed_backtesting_exploration_tpu.rpc.compute import (
            InstantBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            scenario_jobs, synthetic_jobs)
        from distributed_backtesting_exploration_tpu.rpc.panel_store \
            import panel_digest
        from distributed_backtesting_exploration_tpu.rpc.worker import (
            Worker)
        from distributed_backtesting_exploration_tpu.utils import (
            data as s_data)

        s_bars = int(os.environ.get("DBX_BENCH_SCENARIO_BARS", 2048))
        s_n = int(os.environ.get("DBX_BENCH_SCENARIO_N", 32))
        s_series = s_data.synthetic_ohlcv(1, s_bars, seed=900)
        s_blob = s_data.to_wire_bytes(
            type(s_series)(*(np.asarray(f[0]) for f in s_series)))
        params0 = scn_mod.ScenarioParams(block=16, regimes=3,
                                         vol_scale=2.0, shock=0.01)
        # Warm the generator jit: the rate must time steady-state work.
        scn_mod.scenario_panel_bytes(s_blob, params0)
        t0 = time.perf_counter()
        blobs = [scn_mod.scenario_panel_bytes(
            s_blob, dc.replace(params0, seed=i)) for i in range(s_n)]
        gen_elapsed = time.perf_counter() - t0
        redo = scn_mod.scenario_panel_bytes(s_blob,
                                            dc.replace(params0, seed=0))
        deterministic = redo == blobs[0]
        spec_bytes = 32 + s_pb.ScenarioSpec(
            base_digest=panel_digest(s_blob), n_bars=s_bars, block=16,
            regimes=3, vol_scale=2.0, shock=0.01,
            seed=s_n).ByteSize()

        # e2e: the sweep as DISPATCHER work — one real job carries the
        # base panel, the scenario jobs ride as specs and materialize
        # through the panel store at first take.
        queue = JobQueue()
        base_rec = synthetic_jobs(1, 16, "sma_crossover",
                                  {"fast": np.asarray([3.0], np.float32)},
                                  seed=901)[0]
        base_rec.ohlcv = s_blob
        queue.enqueue(base_rec)
        for rec in scenario_jobs(base_rec.panel_digest, s_n,
                                 "sma_crossover",
                                 {"fast": np.arange(5.0, 9.0,
                                                    dtype=np.float32)},
                                 params=params0.to_dict()):
            queue.enqueue(rec)
        with tempfile.TemporaryDirectory() as results_dir:
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                              results_dir=results_dir)
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=0.5).start()
            worker = Worker(f"localhost:{srv.port}", InstantBackend(),
                            worker_id="scenario-bench",
                            poll_interval_s=0.001, status_interval_s=0.5,
                            jobs_per_chip=8)
            wt = threading.Thread(target=worker.run, daemon=True)
            try:
                wt.start()
                t0 = time.perf_counter()
                deadline = time.monotonic() + 600.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit("bench[scenario_sweep]: drain wedged for "
                                 f"600s — stats={queue.stats()}")
                    time.sleep(0.002)
                e2e_rate = (s_n + 1) / (time.perf_counter() - t0)
            finally:
                worker.stop()
                wt.join(timeout=30)
                srv.stop()

        ROOFLINE["scenario_sweep"] = {
            "panels": s_n, "bars": s_bars,
            "gen_s_per_panel": round(gen_elapsed / s_n, 6),
            "panels_per_s": round(s_n / gen_elapsed, 2),
            "bar_rate": round(s_n * s_bars / gen_elapsed, 1),
            "digest_deterministic": bool(deterministic),
            "panel_bytes": len(blobs[0]),
            "spec_bytes": spec_bytes,
            "spec_wire_reduction": round(len(blobs[0])
                                         / max(spec_bytes, 1), 1),
            "jobs_per_s_e2e": round(e2e_rate, 1)}
        rates["scenario_sweep"] = s_n / gen_elapsed
        print(f"bench[scenario_sweep]: {s_n} panels x {s_bars} bars "
              f"generated at {s_n / gen_elapsed:.1f} panels/s "
              f"(deterministic={deterministic}), spec {spec_bytes}B vs "
              f"panel {len(blobs[0])}B, e2e {e2e_rate:.0f} jobs/s",
              file=sys.stderr)

    # --- scenario_megakernel: fused in-trace generation vs materialized ---
    # The round-18 A/B: the SAME scenario sweep drained twice through a
    # real dispatcher+worker loop — once on the spec-batch megakernel
    # route (one carrier JobSpec, panels regenerated in-trace inside the
    # sweep launch, never materialized) and once with the kill switch
    # down (every panel generated host-side, stored, shipped). Two facts
    # ride the JSON: the scenarios/s ratio, and the panel-store
    # bytes-vs-K curve — flat in K for the fused route (only the base
    # panel is content-addressed) and growing for the materialized one.
    if enabled("scenario_megakernel"):
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu.rpc.compute import (
            JaxSweepBackend)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            scenario_jobs, synthetic_jobs)
        from distributed_backtesting_exploration_tpu.rpc.worker import (
            Worker)
        from distributed_backtesting_exploration_tpu.utils import (
            data as mk_data)

        mk_bars = int(os.environ.get("DBX_BENCH_MEGAKERNEL_BARS", 512))
        mk_k = max(int(os.environ.get("DBX_BENCH_MEGAKERNEL_K", 48)), 4)
        mk_grid = {"fast": np.arange(3.0, 7.0, dtype=np.float32),
                   "slow": np.arange(12.0, 44.0, 8.0, dtype=np.float32)}
        mk_combos = int(np.prod([len(v) for v in mk_grid.values()]))
        mk_params = {"n_bars": mk_bars, "block": 16, "regimes": 3,
                     "vol_scale": 2.0, "shock": 0.01}
        mk_series = mk_data.synthetic_ohlcv(1, mk_bars, seed=910)
        mk_blob = mk_data.to_wire_bytes(
            type(mk_series)(*(np.asarray(f[0]) for f in mk_series)))

        def mk_leg(k: int, fused: bool):
            """Drain base + ``k`` scenario jobs through a fresh
            in-process dispatcher + JAX worker on the chosen route;
            returns ``(elapsed_s, panel-store stats at drain)``."""
            prior = os.environ.get("DBX_SCENARIO_FUSED")
            os.environ["DBX_SCENARIO_FUSED"] = "1" if fused else "0"
            try:
                queue = JobQueue()
                base_rec = synthetic_jobs(
                    1, 16, "sma_crossover",
                    {"fast": np.asarray([3.0], np.float32),
                     "slow": np.asarray([12.0], np.float32)}, seed=911)[0]
                base_rec.ohlcv = mk_blob
                queue.enqueue(base_rec)
                for rec in scenario_jobs(base_rec.panel_digest, k,
                                         "sma_crossover", mk_grid,
                                         params=mk_params):
                    queue.enqueue(rec)
                with tempfile.TemporaryDirectory() as results_dir:
                    disp = Dispatcher(queue,
                                      PeerRegistry(prune_window_s=30.0),
                                      results_dir=results_dir)
                    srv = DispatcherServer(disp, bind="localhost:0",
                                           prune_interval_s=0.5).start()
                    # jobs_per_chip >= K+1: one poll takes the whole
                    # sweep, so the fused route coalesces it into ONE
                    # carrier launch (the shape the megakernel serves).
                    worker = Worker(f"localhost:{srv.port}",
                                    JaxSweepBackend(),
                                    worker_id="megakernel-bench",
                                    poll_interval_s=0.001,
                                    status_interval_s=0.5,
                                    jobs_per_chip=k + 1)
                    wt = threading.Thread(target=worker.run, daemon=True)
                    try:
                        wt.start()
                        t0 = time.perf_counter()
                        deadline = time.monotonic() + 600.0
                        while not queue.drained:
                            if time.monotonic() > deadline:
                                sys.exit("bench[scenario_megakernel]: "
                                         f"drain wedged for 600s (fused="
                                         f"{fused}, K={k}) — stats="
                                         f"{queue.stats()}")
                            time.sleep(0.002)
                        elapsed = time.perf_counter() - t0
                    finally:
                        worker.stop()
                        wt.join(timeout=30)
                        srv.stop()
                return elapsed, queue.panel_store.stats()
            finally:
                if prior is None:
                    os.environ.pop("DBX_SCENARIO_FUSED", None)
                else:
                    os.environ["DBX_SCENARIO_FUSED"] = prior

        # Warm both routes at full K first: the fused launch compiles
        # per (K, shape) bucket, so the timed full-K legs must hit a
        # warm cache (smaller curve points compile fresh — their elapsed
        # only annotates the curve, never the headline rates).
        mk_leg(mk_k, True)
        mk_leg(mk_k, False)
        mk_ks = sorted({max(mk_k // 4, 2), max(mk_k // 2, 2), mk_k})
        curve_fused, curve_mat = [], []
        for k in mk_ks:
            el, st = mk_leg(k, True)
            curve_fused.append({"k": k, "elapsed_s": round(el, 4),
                                "store_panels": st["panels"],
                                "store_bytes": st["bytes"]})
        for k in mk_ks:
            el, st = mk_leg(k, False)
            curve_mat.append({"k": k, "elapsed_s": round(el, 4),
                              "store_panels": st["panels"],
                              "store_bytes": st["bytes"]})
        mk_fused_rate = mk_ks[-1] / curve_fused[-1]["elapsed_s"]
        mk_mat_rate = mk_ks[-1] / curve_mat[-1]["elapsed_s"]
        mk_fused_bytes = [p["store_bytes"] for p in curve_fused]
        ROOFLINE["scenario_megakernel"] = {
            "scenarios": mk_k, "bars": mk_bars, "combos": mk_combos,
            "fused_scn_per_s": round(mk_fused_rate, 2),
            "materialized_scn_per_s": round(mk_mat_rate, 2),
            "speedup": round(mk_fused_rate / max(mk_mat_rate, 1e-9), 2),
            "store_bytes_by_k_fused": curve_fused,
            "store_bytes_by_k_materialized": curve_mat,
            # O(1)-in-K device/store residency: the fused curve holds
            # exactly the base panel at every K.
            "store_bytes_flat_in_k": bool(
                max(mk_fused_bytes) == min(mk_fused_bytes)),
        }
        rates["scenario_megakernel"] = mk_fused_rate
        print(f"bench[scenario_megakernel]: {mk_k} scenarios x "
              f"{mk_combos} combos @ {mk_bars} bars -> fused "
              f"{mk_fused_rate:.1f} scn/s vs materialized "
              f"{mk_mat_rate:.1f} scn/s "
              f"({mk_fused_rate / max(mk_mat_rate, 1e-9):.2f}x), store "
              f"bytes flat in K: "
              f"{max(mk_fused_bytes) == min(mk_fused_bytes)}",
              file=sys.stderr)

    # --- configs[4]: walk-forward (12 refit windows x grid) ---------------
    if enabled("walkforward"):
        train = n_bars // 2 - 30
        test = max((n_bars - train) // 12, 1)
        wgrid = sweep.product_grid(
            fast=jnp.arange(5, 25, dtype=jnp.float32),
            slow=jnp.arange(30, 130, 5, dtype=jnp.float32))
        n_windows = int((n_bars - train) // test)
        strat = base.get_strategy("sma_crossover")

        import functools
        from types import SimpleNamespace

        # Generic walk_forward is ONE fused XLA program end to end and wins
        # at this grid size (11.5M/s vs 5.5M/s measured for the
        # walk_forward_fused two-phase split at P=400 — the fused train
        # kernel only pays off at much larger param grids). Set
        # DBX_BENCH_WF_FUSED=1 to measure the fused variant.
        if os.environ.get("DBX_BENCH_WF_FUSED") == "1":
            wfa = np.asarray(wgrid["fast"])
            wsl = np.asarray(wgrid["slow"])

            def run_wf():
                r = walkforward.walk_forward_fused(
                    panel, strat, wgrid,
                    functools.partial(fused.fused_sma_sweep, fast=wfa,
                                      slow=wsl, cost=1e-3),
                    train=train, test=test, cost=1e-3)
                return SimpleNamespace(sharpe=r.oos_metrics.sharpe)
        else:
            def run_wf():
                r = walkforward.walk_forward(
                    panel, strat, wgrid, train=train, test=test, cost=1e-3)
                return SimpleNamespace(sharpe=r.oos_metrics.sharpe)

        rates["walkforward"] = _measure(
            run_wf, n_tickers * sweep.grid_size(wgrid) * n_windows,
            iters=max(iters // 2, 3), warmup=max(warmup // 3, 2),
            name="walkforward")

    # --- long-context: one >64k-bar history through the serving path -----
    # (VERDICT r4 item 1: the route a worker takes for jobs beyond the
    # fused VMEM cap. On a multi-chip host the bar axis shards over the
    # chips via parallel.timeshard — the same code rpc.compute routes to;
    # on one chip it is the generic sweep that single-chip workers serve.)
    if enabled("long_context"):
        lc_bars = int(os.environ.get("DBX_BENCH_LC_BARS", 65537))
        lc_grid = sweep.product_grid(
            fast=jnp.arange(5, 13, dtype=jnp.float32),
            slow=jnp.arange(30, 70, 10, dtype=jnp.float32))   # P = 32
        lc_ohlcv = data.synthetic_ohlcv(1, lc_bars, seed=7)
        lc_strat = base.get_strategy("sma_crossover")
        lc_devs = jax.devices()
        if len(lc_devs) > 1:
            from jax.sharding import (
                Mesh, NamedSharding, PartitionSpec as Pspec)

            from distributed_backtesting_exploration_tpu.parallel import (
                timeshard)

            T_pad = -(-lc_bars // len(lc_devs)) * len(lc_devs)
            close_np = np.asarray(lc_ohlcv.close, np.float32)
            if T_pad > lc_bars:
                close_np = np.concatenate(
                    [close_np,
                     np.repeat(close_np[:, -1:], T_pad - lc_bars, 1)], 1)
            tmesh = Mesh(np.asarray(lc_devs), (timeshard.TIME_AXIS,))
            sh_close = jax.device_put(
                close_np,
                NamedSharding(tmesh, Pspec(None, timeshard.TIME_AXIS)))
            lc_combos = [
                (int(f), int(s))
                for f, s in zip(np.asarray(lc_grid["fast"]),
                                np.asarray(lc_grid["slow"]))]
            lc_tr = None if T_pad == lc_bars else lc_bars

            @jax.jit
            def _run_lc_sharded(c):
                ms = [timeshard.sharded_sma_backtest(
                          tmesh, c, f, s, cost=1e-3, t_real=lc_tr)
                      for f, s in lc_combos]
                return jnp.stack([m.sharpe for m in ms], axis=-1)

            def run_lc():
                from types import SimpleNamespace
                return SimpleNamespace(sharpe=_run_lc_sharded(sh_close))
        else:
            lc_panel = type(lc_ohlcv)(
                *(jax.device_put(jnp.asarray(f), dev) for f in lc_ohlcv))

            def run_lc():
                return sweep.jit_sweep(lc_panel, lc_strat, lc_grid,
                                       cost=1e-3)

        rates["long_context"] = _measure(
            run_lc, sweep.grid_size(lc_grid), iters=max(iters // 2, 3),
            warmup=max(warmup // 3, 2), name="long_context")
        print(f"bench[long_context]: {lc_bars} bars x "
              f"{sweep.grid_size(lc_grid)} params on {len(lc_devs)} "
              f"device(s) -> "
              f"{rates['long_context'] * lc_bars / 1e6:.1f}M bar-backtests/s",
              file=sys.stderr)

    # --- ragged_paged: mixed-length fleet through the device page pool ----
    # ROADMAP item 2's acceptance instrument: a log-spaced mixed-length
    # universe swept through the page tables (fused_paged_sweep — one
    # launch per page-count class, pad bounded by one page per ticker)
    # vs the SAME total bar count as one uniform-length dense sweep.
    # `paged_vs_uniform_ratio` is the <=1.3x acceptance number;
    # `launches_*`/`pad_bars_*` record what the paged schedule saves over
    # the dense power-of-two length bucketing (the pre-round-10 grouping
    # rule, reproduced here from the wire byte-length formula), and
    # `pool_bytes_per_ticker` the device-residency cost.
    if enabled("ragged_paged"):
        from distributed_backtesting_exploration_tpu.rpc.page_pool import (
            PagePool)

        rp_n = int(os.environ.get("DBX_BENCH_RAGGED_TICKERS", 1024))
        rp_spread = float(os.environ.get("DBX_BENCH_RAGGED_SPREAD", 8))
        rp_tmax = n_bars
        rp_B = fused.resolve_page_bars()
        rp_lens = np.unique(np.round(np.geomspace(
            max(rp_tmax / rp_spread, 64), rp_tmax, rp_n)).astype(np.int64),
            return_inverse=False)
        # geomspace collapses duplicates at tiny scales; tile back to rp_n.
        rp_lens = np.sort(np.resize(rp_lens, rp_n))
        rp_total = int(rp_lens.sum())
        rp_Tu = max(int(rp_total // rp_n), 64)
        rgrid = {k: np.asarray(v) for k, v in sweep.product_grid(
            fast=np.arange(5.0, 13.0, dtype=np.float32),
            slow=np.arange(30.0, 46.0, 4.0, dtype=np.float32)).items()}
        rp_P = int(rgrid["fast"].size)

        rp_panel = data.synthetic_ohlcv(rp_n, rp_tmax, seed=11)
        rp_close = np.asarray(rp_panel.close, np.float32)
        rp_series = [data.OHLCV(*(np.asarray(f, np.float32)[i, :t]
                                  for f in rp_panel))
                     for i, t in enumerate(rp_lens)]
        rp_pool = PagePool(
            max_bytes=2 * rp_n * (-(-rp_tmax // rp_B)) * rp_B * 4)
        prep = rp_pool.prepare([f"rp{i}" for i in range(rp_n)], rp_series,
                               ("close",))
        if prep is None:
            sys.exit("bench[ragged_paged]: page pool rejected the fleet")
        rp_pool_arr, rp_tables, _ = prep
        rp_treal = np.asarray(rp_lens, np.int32)

        from types import SimpleNamespace

        def run_paged():
            m = fused.fused_paged_sweep(
                "sma_crossover", rp_pool_arr, rp_tables, rp_treal, rgrid,
                cost=1e-3)
            return SimpleNamespace(sharpe=m.sharpe)

        def run_uniform():
            m = fused.fused_sma_sweep(rp_close[:, :rp_Tu], rgrid["fast"],
                                      rgrid["slow"], cost=1e-3)
            return SimpleNamespace(sharpe=m.sharpe)

        rp_iters = max(min(iters, 5), 2)
        rp_warm = max(min(warmup, 2), 1)
        rate_paged = _measure(run_paged, rp_n * rp_P, iters=rp_iters,
                              warmup=rp_warm, name="ragged_paged")
        rate_uni = _measure(run_uniform, rp_n * rp_P, iters=rp_iters,
                            warmup=rp_warm, name="ragged_paged_uniform")
        t_paged = rp_n * rp_P / rate_paged
        t_uni = rp_n * rp_P / rate_uni

        # Dense-bucketing counterfactual (the pre-round-10 grouping rule:
        # power-of-two buckets on the DBX1 wire byte length, then each
        # bucket repeat-last padded to its own max).
        wire_len = 8 + 4 * 5 * rp_lens          # DBX1: magic+T+5 f32[T]
        buckets: dict = {}
        for t, wl in zip(rp_lens, wire_len):
            buckets.setdefault(int(wl).bit_length(), []).append(int(t))
        pad_dense = sum(max(ts) * len(ts) - sum(ts)
                        for ts in buckets.values())
        pages_per = -(-rp_lens // rp_B)
        pad_paged = int((pages_per * rp_B - rp_lens).sum())
        launches_paged = int(np.unique(pages_per).size)
        pool_stats = rp_pool.stats()

        ratio = t_paged / max(t_uni, 1e-9)
        ROOFLINE["ragged_paged"] = {
            "tickers": rp_n, "t_max": int(rp_lens.max()),
            "t_min": int(rp_lens.min()), "total_bars": rp_total,
            "uniform_bars": rp_Tu, "combos": rp_P,
            "page_bars": rp_B,
            "paged_s_per_sweep": round(t_paged, 6),
            "uniform_s_per_sweep": round(t_uni, 6),
            "paged_vs_uniform_ratio": round(ratio, 3),
            "ratio_ok": bool(ratio <= 1.3),
            "launches_dense": len(buckets),
            "launches_paged": launches_paged,
            "pad_bars_dense": int(pad_dense),
            "pad_bars_paged": pad_paged,
            "pool_bytes": pool_stats["bytes"],
            "pool_bytes_per_ticker": round(pool_stats["bytes"] / rp_n, 1),
        }
        rates["ragged_paged"] = rate_paged
        print(f"bench[ragged_paged]: {rp_n} tickers x {rp_P} combos, "
              f"lengths {int(rp_lens.min())}..{int(rp_lens.max())} "
              f"(B={rp_B}): paged/uniform {ratio:.2f}x, launches "
              f"{len(buckets)} dense -> {launches_paged} paged, pad bars "
              f"{pad_dense} -> {pad_paged}", file=sys.stderr)

    # --- autotune: substrate autotuner + fleet-shared compile cache -------
    # ROADMAP item 4's acceptance instrument, two halves:
    # (a) per-family A/B of the hardcoded substrate defaults vs the
    #     autotuner's measured winner for this (shape-bucket, platform) —
    #     `autotuned_vs_default_speedup{family}` records the MEASURED
    #     ratio on this box plus the deterministic MODELED twin from the
    #     op-model prior (the on-chip expectation, recorded like PR 3's
    #     modeled acceptance when no chip is in the round's loop);
    # (b) the fleet compile-cache cold-start A/B: worker A pays a cold
    #     compile into a fresh persistent-cache dir, offers the entries
    #     over the REAL OfferCompiled RPC, worker B fetches + installs
    #     into its own fresh dir and re-compiles the same program —
    #     `second_worker_compile_wall_{cold,warm}_s` and
    #     `compile_wall_reduction` are the >=5x acceptance numbers.
    if enabled("autotune"):
        import contextlib
        import tempfile
        import threading

        from distributed_backtesting_exploration_tpu import (
            tune as tune_mod)
        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as at_pb, service as at_service)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry)

        at_bars = int(os.environ.get("DBX_BENCH_AUTOTUNE_BARS", 512))
        at_tickers = int(os.environ.get("DBX_BENCH_AUTOTUNE_TICKERS", 4))
        at_reps = max(min(iters, 5), 2)
        at_panel = data.synthetic_ohlcv(at_tickers, at_bars, seed=21)
        at_close = np.asarray(at_panel.close, np.float32)
        at_hi = np.asarray(at_panel.high, np.float32)
        at_lo = np.asarray(at_panel.low, np.float32)

        fa16 = np.tile(np.arange(3.0, 7.0, dtype=np.float32), 4)
        sl16 = np.repeat(np.arange(10.0, 18.0, 2.0,
                                   dtype=np.float32), 4)
        w16 = np.tile(np.arange(4.0, 8.0, dtype=np.float32), 4)
        k16 = np.repeat(np.linspace(0.5, 2.0, 4,
                                    dtype=np.float32), 4)
        lb16 = np.arange(2.0, 18.0, dtype=np.float32)
        at_cases = {
            "sma_crossover": lambda **kw: fused.fused_sma_sweep(
                at_close, fa16, sl16, cost=1e-3, **kw),
            "bollinger": lambda **kw: fused.fused_bollinger_sweep(
                at_close, w16, k16, cost=1e-3, **kw),
            "momentum": lambda **kw: fused.fused_momentum_sweep(
                at_close, lb16, cost=1e-3, **kw),
            "stochastic": lambda **kw: fused.fused_stochastic_sweep(
                at_close, at_hi, at_lo, w16, k16 * 20 + 40, cost=1e-3,
                **kw),
            "obv_trend": lambda **kw: fused.fused_obv_sweep(
                at_close,
                np.asarray(at_panel.volume, np.float32), w16 + 2,
                cost=1e-3, **kw),
        }

        def at_wall(run, substrates=None):
            ctx = (fused.tuned_schedule(substrates) if substrates
                   else contextlib.nullcontext())
            with ctx:
                jax.block_until_ready(run().sharpe)   # compile + warm
                t0 = time.perf_counter()
                for _ in range(at_reps):
                    jax.block_until_ready(run().sharpe)
                return (time.perf_counter() - t0) / at_reps

        prior_mode = os.environ.get("DBX_AUTOTUNE")
        os.environ["DBX_AUTOTUNE"] = prior_mode or "1"
        at_sched = tune_mod.ScheduleRegistry()
        tuner = tune_mod.Autotuner(at_sched)
        fam_rows = {}
        speedups, speedups_modeled = {}, {}
        try:
            platform = jax.default_backend()
            for fam, run in at_cases.items():
                n_combos = 16
                bucket = tune_mod.shape_bucket(at_bars, n_combos)
                winner = tuner.tune(
                    fam, bucket, platform, n_bars=at_bars,
                    n_combos=n_combos,
                    measure=lambda subs, run=run: at_wall(run, subs))
                t_default = at_wall(run)
                t_tuned = at_wall(run, winner)
                defaults = fused.substrate_defaults()
                d_subs = {"epilogue": defaults["epilogue"],
                          "lanes_cap": defaults["lanes_cap"]}
                tf = fused._STRATEGY_TABLE_FAMILY.get(fam)
                if tf:
                    d_subs[f"table_{tf}"] = defaults[f"table_{tf}"]
                m_default = tune_mod.modeled_cost(
                    fam, d_subs, n_bars=at_bars, n_combos=n_combos)
                m_tuned = tune_mod.modeled_cost(
                    fam, winner or d_subs, n_bars=at_bars,
                    n_combos=n_combos)
                speedups[fam] = round(t_default / max(t_tuned, 1e-9), 3)
                speedups_modeled[fam] = round(
                    m_default / max(m_tuned, 1e-9), 3)
                fam_rows[fam] = {
                    "bucket": bucket,
                    "default_s_per_sweep": round(t_default, 6),
                    "tuned_s_per_sweep": round(t_tuned, 6),
                    "substrates": winner,
                }
                print(f"bench[autotune:{fam}]: default "
                      f"{t_default * 1e3:.2f} ms -> tuned "
                      f"{t_tuned * 1e3:.2f} ms ({speedups[fam]:.2f}x, "
                      f"modeled {speedups_modeled[fam]:.2f}x) "
                      f"{winner}", file=sys.stderr)
        finally:
            if prior_mode is None:
                os.environ.pop("DBX_AUTOTUNE", None)
            else:
                os.environ["DBX_AUTOTUNE"] = prior_mode

        # (b) fleet compile-cache cold-start A/B over real RPCs.
        depth = int(os.environ.get("DBX_BENCH_AUTOTUNE_COMPILE_DEPTH",
                                   48))

        def compile_probe():
            w = jnp.eye(64, dtype=jnp.float32) * 1.001

            @jax.jit
            def prog(x):
                acc = x
                for i in range(depth):
                    acc = jnp.tanh(acc @ w + np.float32(i) * 1e-3)
                return acc.sum()
            t0 = time.perf_counter()
            jax.block_until_ready(
                prog(jnp.ones((64, 64), jnp.float32)))
            return time.perf_counter() - t0

        queue = JobQueue()
        disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0))
        srv = DispatcherServer(disp, bind="localhost:0",
                               prune_interval_s=5.0).start()
        import grpc as at_grpc

        prior_cache_dir = getattr(jax.config,
                                  "jax_compilation_cache_dir", None)
        tmp_root = tempfile.mkdtemp(prefix="dbx-autotune-cache-")
        try:
            channel = at_grpc.insecure_channel(
                f"localhost:{srv.port}",
                options=at_service.default_channel_options())
            stub = at_service.DispatcherStub(channel)
            # Both "workers" use the SAME canonical cache path — the
            # runtime default_cache_dir() is the same path on every host,
            # and jax's persistent-cache key folds the configured dir
            # path (measured on this jax generation: identical program,
            # different dir -> different key), so fleet sharing is
            # defined over the canonical path. Worker B is modeled as a
            # different host: the dir is WIPED (its own disk is cold)
            # and repopulated only by the fleet fetch.
            cache_path = os.path.join(tmp_root, "cache")
            sync_a = tune_mod.CacheSync(cache_path)
            tune_mod.configure(cache_path, min_compile_time_s=0.0)
            jax.clear_caches()
            wall_cold = compile_probe()
            offers = sync_a.poll_new()
            if offers:
                stub.OfferCompiled(at_pb.CompiledOffer(
                    worker_id="bench-a",
                    entries=[at_pb.CompiledEntry(key=k, name=n,
                                                 payload=p)
                             for k, n, p in offers]))
            # Worker B: cold disk, fleet-warmed cache.
            import shutil

            shutil.rmtree(cache_path, ignore_errors=True)
            sync_b = tune_mod.CacheSync(cache_path)
            listing = stub.FetchCompiled(at_pb.CompiledRequest(
                worker_id="bench-b"))
            miss = sync_b.missing(listing.known_keys)
            installed = 0
            if miss:
                got = stub.FetchCompiled(at_pb.CompiledRequest(
                    worker_id="bench-b", keys=miss))
                installed = sync_b.install(
                    (e.key, e.name, e.payload) for e in got.entries)
            tune_mod.configure(cache_path, min_compile_time_s=0.0)
            jax.clear_caches()
            wall_warm = compile_probe()
            channel.close()
        finally:
            srv.stop()
            # Restore the prior cache config (or the canonical default —
            # leaving jax pointed at the deleted tmp dir would break
            # persistent-cache writes for the rest of the run).
            tune_mod.configure(prior_cache_dir
                               or tune_mod.default_cache_dir())
            import shutil

            shutil.rmtree(tmp_root, ignore_errors=True)

        reduction = wall_cold / max(wall_warm, 1e-9)
        store_stats = disp.compile_store.stats()
        ROOFLINE["autotune"] = {
            "bars": at_bars, "tickers": at_tickers, "combos": 16,
            "platform": platform,
            "autotuned_vs_default_speedup": speedups,
            "autotuned_vs_default_speedup_modeled": speedups_modeled,
            "families": fam_rows,
            "speedup_families_ok": sum(
                1 for v in speedups.values() if v >= 1.2),
            "second_worker_compile_wall_cold_s": round(wall_cold, 4),
            "second_worker_compile_wall_warm_s": round(wall_warm, 4),
            "compile_wall_reduction": round(reduction, 2),
            "fleet_entries_offered": len(offers),
            "fleet_entries_installed": installed,
            "fleet_store_bytes": store_stats["bytes"],
        }
        rates["autotune"] = 1.0 / max(
            sum(r["tuned_s_per_sweep"] for r in fam_rows.values()), 1e-9)
        print(f"bench[autotune]: speedups {speedups} (modeled "
              f"{speedups_modeled}); second-worker compile wall "
              f"{wall_cold * 1e3:.0f} ms cold -> {wall_warm * 1e3:.0f} ms "
              f"fleet-warm ({reduction:.1f}x, {installed} entries "
              f"installed)", file=sys.stderr)

    # --- pipeline: the round-14 double-buffered executor A/B --------------
    # Saturated-worker e2e: ONE worker drains the same distinct-panel
    # workload under the serial compute loop (DBX_PIPELINE=0 — the
    # round-13 worker) and under the pipelined executor (DBX_PIPELINE=1).
    # DBX_PREFETCH is pinned OFF in both arms: the staged backend has no
    # prefetch hook, so leaving it on would label the A/B as covering a
    # leg that never executes (the prefetch legs get their coverage from
    # the integration tests and the live-worker drive). jobs/s is the
    # acceptance headline; the overlap-aware timeline digest
    # (summarize_spans(..., overlap=True) over the span ring) is the
    # mechanism check — submit+collect lane seconds per covered wall
    # second on the worker — and the per-stage attribution before/after
    # shows where the serial wall went.
    #
    # The backend is the calibrated staged replay below, NOT the live
    # jax backend: on this CPU twin the XLA "device" IS the host core,
    # so with real kernels a pipelined A/B measures one core's scheduler
    # contention (two sweeps time-slicing), not executor overlap — the
    # same reason e2e_local instruments the control plane with
    # InstantBackend. The host staging wall is CALIBRATED from the real
    # jax backend's measured submit wall on this exact workload; the
    # device execute+d2h wall is modeled as a GIL-free wait (a real
    # accelerator computes without the host). The on-chip round
    # re-records this config with the live backend (ROADMAP caveat).
    if enabled("pipeline"):
        import threading

        from distributed_backtesting_exploration_tpu.obs import (
            timeline as tl_mod)
        from distributed_backtesting_exploration_tpu.utils import (
            data as dbx_data)
        from distributed_backtesting_exploration_tpu.ops.metrics import (
            Metrics as _Metrics)
        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as pb, compute as compute_mod, wire as wire_mod)
        from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
            Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
            synthetic_jobs)
        from distributed_backtesting_exploration_tpu.rpc.worker import (
            Worker)

        p_jobs = int(os.environ.get("DBX_BENCH_PIPELINE_JOBS", 64))
        # Bars bound the per-job wire payload: past ~1k bars the gzip'd
        # RequestJobs replies take longer than a batch's compute on this
        # box, the input channel never buffers ahead, and the A/B
        # measures the control plane instead of the executor.
        p_bars = int(os.environ.get("DBX_BENCH_PIPELINE_BARS", 512))
        p_fast = int(os.environ.get("DBX_BENCH_PIPELINE_FAST", 8))
        p_slow = int(os.environ.get("DBX_BENCH_PIPELINE_SLOW", 8))
        p_batch = int(os.environ.get("DBX_BENCH_PIPELINE_BATCH", 4))
        # 0 = balanced (device wall == calibrated host wall): the regime
        # double buffering targets — overlap at any other ratio is
        # bounded by min(host, device)/max(host, device).
        p_device_ms = float(os.environ.get("DBX_BENCH_PIPELINE_DEVICE_MS",
                                           0.0))
        p_grid = {
            "fast": np.arange(2.0, 2.0 + p_fast, dtype=np.float32),
            "slow": np.arange(32.0, 32.0 + 2 * p_slow, 2,
                              dtype=np.float32)}

        # Calibration: the real backend's warm submit wall (decode +
        # stack + jit dispatch) for this exact batch shape — the host
        # staging wall the staged backend replays.
        cal_recs = synthetic_jobs(p_batch, p_bars, "sma_crossover",
                                  p_grid, seed=6999)
        cal_specs = [pb.JobSpec(id=r.id, strategy=r.strategy,
                                ohlcv=r.ohlcv,
                                grid=wire_mod.grid_to_proto(r.grid),
                                cost=r.cost, periods_per_year=252)
                     for r in cal_recs]
        cal = compute_mod.JaxSweepBackend(use_fused=False)
        for _ in range(2):
            cal.collect(cal.submit(cal_specs))      # compile + warm
        cal_walls = []
        for _ in range(5):
            t0 = time.perf_counter()
            h = cal.submit(cal_specs)
            cal_walls.append(time.perf_counter() - t0)
            cal.collect(h)
        # Floor the replayed wall: the loopback control plane adds
        # ms-scale jitter per batch (polls, gzip, GIL handoffs on the
        # 1-core box), and with stage walls near that scale the A/B
        # measures the jitter, not the executor. The floor keeps the
        # calibrated PROFILE (balanced stages) while making the stage
        # walls dominate what they are divided by.
        p_host_floor_ms = float(os.environ.get(
            "DBX_BENCH_PIPELINE_HOST_FLOOR_MS", 12.0))
        host_s = max(sorted(cal_walls)[len(cal_walls) // 2],
                     p_host_floor_ms / 1e3)
        device_s = p_device_ms / 1e3 if p_device_ms > 0 else host_s

        _empty_dbxm = wire_mod.metrics_to_bytes(_Metrics(
            *(np.zeros(1, np.float32) for _ in _Metrics._fields)))

        class _StagedPipelineBackend:
            """Replays the calibrated host staging wall with real array
            work over the actual payloads (wire decode + per-field
            stacks, re-stacked until the measured wall elapses) and
            models the device execute+d2h wall as a deadline wait. Emits
            the real worker.decode / worker.d2h spans so the timeline
            digest attributes stages for BOTH loop modes."""

            chips = 1

            def submit(self, jobs):
                jobs = list(jobs)
                pairs = _obs.job_trace_pairs(jobs)
                t0_wall, t0 = time.time(), time.perf_counter()
                deadline = t0 + host_s
                series = [dbx_data.from_wire_bytes(j.ohlcv) for j in jobs]
                while True:
                    [np.stack([np.asarray(getattr(s, f), np.float32)
                               for s in series])
                     for f in ("close", "high", "low")]
                    if time.perf_counter() >= deadline:
                        break
                _obs.emit_span("worker.decode", t0_wall,
                               time.perf_counter() - t0, pairs=pairs,
                               jobs=len(jobs), cache_hit=False)
                return jobs, time.monotonic() + device_s

            def collect(self, handle):
                jobs, t_done = handle
                pairs = _obs.job_trace_pairs(jobs)
                t0_wall, t0 = time.time(), time.perf_counter()
                delay = t_done - time.monotonic()
                if delay > 0:
                    time.sleep(delay)   # the device computes host-free
                out = [compute_mod.Completion(j.id, _empty_dbxm, device_s,
                                              trace_id=j.trace_id)
                       for j in jobs]
                _obs.emit_span("worker.d2h", t0_wall,
                               time.perf_counter() - t0, pairs=pairs,
                               jobs=len(jobs), cache_hit=False)
                return out

            def process(self, jobs):
                return self.collect(self.submit(jobs))

        def run_pipeline_mode(pipeline_on: bool):
            """One saturated-worker drain; returns (jobs/s, overlap-aware
            timeline digest of the measured window)."""
            prior = {k: os.environ.get(k)
                     for k in ("DBX_PIPELINE", "DBX_PREFETCH")}
            os.environ["DBX_PIPELINE"] = "1" if pipeline_on else "0"
            os.environ["DBX_PREFETCH"] = "0"
            queue = JobQueue()
            disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0))
            srv = DispatcherServer(disp, bind="localhost:0",
                                   prune_interval_s=1.0).start()
            backend = _StagedPipelineBackend()
            # max_inflight_batches=4: the input channel buffers ahead of
            # the depth-2 pipeline, so a slow poll (gzip'd replies on a
            # loaded core) starves neither loop mode.
            w = Worker(f"localhost:{srv.port}", backend,
                       poll_interval_s=0.001, status_interval_s=0.5,
                       jobs_per_chip=p_batch, max_inflight_batches=4)
            t = threading.Thread(target=w.run, daemon=True)
            seed0 = 7000 if pipeline_on else 8000

            def drain(n, seed):
                for rec in synthetic_jobs(n, p_bars, "sma_crossover",
                                          p_grid, seed=seed):
                    queue.enqueue(rec)
                deadline = time.monotonic() + 600.0
                while not queue.drained:
                    if time.monotonic() > deadline:
                        sys.exit("bench[pipeline]: drain wedged for 600s "
                                 f"— stats={queue.stats()}")
                    time.sleep(0.005)

            try:
                t.start()
                # Warm-up drain: compiles + channel warm, outside the clock.
                drain(max(p_jobs // 4, p_batch * 3), seed0)
                # Fresh ring (same DBX_SPAN_RING capacity) so the overlap
                # digest covers ONLY the measured window of THIS mode.
                _obs.configure_ring()
                t0 = time.perf_counter()
                drain(p_jobs, seed0 + 1)
                elapsed = time.perf_counter() - t0
            finally:
                w.stop()
                t.join(timeout=60)
                srv.stop()
                for k, v in prior.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            digest = tl_mod.summarize_spans(_obs.recent_spans(),
                                            overlap=True)
            return p_jobs / elapsed, digest

        r_serial, tl_serial = run_pipeline_mode(False)
        r_piped, tl_piped = run_pipeline_mode(True)
        _obs.configure_ring()   # end-of-run digest: not this A/B's

        def _stage_totals(tl):
            return {k: v["total_s"]
                    for k, v in tl.get("stages", {}).items()
                    if v["total_s"] > 0}

        ov_piped = tl_piped.get("overlap", {}).get("overlap_factor", 1.0)
        ov_serial = tl_serial.get("overlap", {}).get("overlap_factor", 1.0)
        rates["pipeline"] = r_piped
        ROOFLINE["pipeline"] = {
            "jobs": p_jobs, "bars": p_bars,
            "combos_per_job": p_fast * p_slow, "batch": p_batch,
            "host_stage_ms": round(host_s * 1e3, 3),
            "device_stage_ms": round(device_s * 1e3, 3),
            "jobs_per_s_serial": round(r_serial, 2),
            "jobs_per_s_pipelined": round(r_piped, 2),
            "pipeline_speedup": round(r_piped / max(r_serial, 1e-9), 3),
            "overlap_factor": round(ov_piped, 3),
            "overlap_factor_serial": round(ov_serial, 3),
            "stages_serial": _stage_totals(tl_serial),
            "stages_pipelined": _stage_totals(tl_piped),
        }
        print(f"bench[pipeline]: {p_jobs} jobs x {p_fast * p_slow} combos "
              f"@ {p_bars} bars, batch={p_batch} -> serial "
              f"{r_serial:.2f} jobs/s, pipelined {r_piped:.2f} jobs/s "
              f"({r_piped / max(r_serial, 1e-9):.2f}x), overlap "
              f"{ov_serial:.2f} -> {ov_piped:.2f}", file=sys.stderr)

    if not rates:
        known = ("sma_fused, bollinger_fused, bollinger_touch_fused, "
                 "momentum_fused, donchian_fused, donchian_hl_fused, "
                 "keltner_fused, stochastic_fused, vwap_fused, rsi_fused, "
                 "macd_fused, trix_fused, obv_fused, pairs, e2e, e2e_topk, "
                 "e2e_local, e2e_local_tenants, scenario_sweep, "
                 "scenario_megakernel, "
                 "direct_dispatch, queue_machine, streaming_append, "
                 "fanout, ragged_paged, autotune, walkforward, "
                 "long_context, roofline_stages, pipeline, "
                 "fleet_telemetry, certify")
        sys.exit(f"bench: no configs ran — DBX_BENCH_CONFIGS={only} matched "
                 f"nothing (known: {known})")
    # The headline is the north-star config when it ran; otherwise label the
    # line with whichever config it actually reports (a DBX_BENCH_CONFIGS
    # subset must not masquerade as the SMA headline).
    headline_name = ("sma_fused" if "sma_fused" in rates
                     else next(iter(rates)))
    if headline_name == "sma_fused":
        metric = ("backtests/sec/chip (ticker x param combos), "
                  "SMA-crossover sweep, 5y daily bars")
    else:
        metric = (f"backtests/sec/chip (ticker x param combos), "
                  f"config={headline_name}")
    # Live per-phase attribution: the obs registry every configured layer
    # recorded into during this run (RPC latency histograms from the e2e /
    # direct-dispatch configs, decode/submit/collect splits and kernel
    # wall from the worker backend, journal fsync timing). Snapshotting it
    # into BENCH JSON gives the roofline numbers their runtime
    # counterparts (metric names in DESIGN.md "Observability").
    from distributed_backtesting_exploration_tpu import obs as obs_mod
    from distributed_backtesting_exploration_tpu.obs import (
        timeline as timeline_mod)

    print(json.dumps({
        "metric": metric,
        "value": round(rates[headline_name], 1),
        "unit": "backtests/sec",
        # reference worker: 1 backtest/sec
        "vs_baseline": round(rates[headline_name], 1),
        "configs": {k: round(v, 1) for k, v in rates.items()},
        # Per-kernel utilization model (% of approximate v5e peaks +
        # binding resource); see the roofline comment in main().
        "roofline": ROOFLINE,
        "obs": obs_mod.get_registry().summaries(prefix="dbx_"),
        # Distributed-trace digest of the e2e configs: the dispatcher+
        # worker loops run in-process, so the completed-span ring already
        # holds every job's stitched timeline — critical-path stage
        # attribution (queue-wait/dispatch/transport/decode/compile/
        # execute/d2h/report) and straggler flags, no JSONL file needed.
        # {} when no traced e2e config ran (kernel-only benches).
        "timeline": timeline_mod.summarize_spans(obs_mod.recent_spans()),
    }))


def verify():
    """Fused-vs-generic parity ON THE CHIP: max rel err + flip rates."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from distributed_backtesting_exploration_tpu.models import base, pairs
    from distributed_backtesting_exploration_tpu.ops import fused
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    n_tickers = int(os.environ.get("DBX_BENCH_TICKERS", 100))
    n_bars = int(os.environ.get("DBX_BENCH_BARS", 1260))
    dev = jax.devices()[0]
    ohlcv = data.synthetic_ohlcv(n_tickers, n_bars, seed=0)
    panel = type(ohlcv)(*(jax.device_put(jnp.asarray(f), dev) for f in ohlcv))
    out = {"device": dev.device_kind}

    def strat_case(strat_name, grid, run_fused):
        return (lambda: sweep.jit_sweep(panel, base.get_strategy(strat_name),
                                        dict(grid), cost=1e-3),
                lambda: run_fused(grid))

    if n_tickers < 2:
        sys.exit("bench --verify: the pairs case needs DBX_BENCH_TICKERS >= 2 "
                 "(each pair takes two ticker series)")
    n_pairs = n_tickers // 2
    y_close, x_close = panel.close[:n_pairs], panel.close[n_pairs:2 * n_pairs]
    pgrid = sweep.product_grid(
        lookback=jnp.arange(10, 50, 2, dtype=jnp.float32),
        z_entry=jnp.linspace(0.5, 3.0, 20).astype(jnp.float32))

    cases = {
        "sma": strat_case(
            "sma_crossover",
            sweep.product_grid(
                fast=jnp.arange(5, 25, dtype=jnp.float32),
                slow=jnp.arange(30, 70, 2, dtype=jnp.float32)),
            lambda g: fused.fused_sma_sweep(
                panel.close, np.asarray(g["fast"]), np.asarray(g["slow"]),
                cost=1e-3),
        ),
        "bollinger": strat_case(
            "bollinger",
            sweep.product_grid(
                k=jnp.linspace(0.5, 3.0, 20).astype(jnp.float32),
                window=jnp.arange(10, 50, 2, dtype=jnp.float32)),
            lambda g: fused.fused_bollinger_sweep(
                panel.close, np.asarray(g["window"]), np.asarray(g["k"]),
                cost=1e-3),
        ),
        "momentum": strat_case(
            "momentum",
            sweep.product_grid(
                lookback=jnp.arange(5, 85, 2, dtype=jnp.float32)),
            lambda g: fused.fused_momentum_sweep(
                panel.close, np.asarray(g["lookback"]), cost=1e-3),
        ),
        "bollinger_touch": strat_case(
            "bollinger_touch",
            sweep.product_grid(
                k=jnp.linspace(0.5, 3.0, 20).astype(jnp.float32),
                window=jnp.arange(10, 50, 2, dtype=jnp.float32)),
            lambda g: fused.fused_bollinger_touch_sweep(
                panel.close, np.asarray(g["window"]), np.asarray(g["k"]),
                cost=1e-3),
        ),
        "donchian": strat_case(
            "donchian",
            sweep.product_grid(
                window=jnp.arange(10, 90, 2, dtype=jnp.float32)),
            lambda g: fused.fused_donchian_sweep(
                panel.close, np.asarray(g["window"]), cost=1e-3),
        ),
        "donchian_hl": strat_case(
            "donchian_hl",
            sweep.product_grid(
                window=jnp.arange(10, 90, 2, dtype=jnp.float32)),
            lambda g: fused.fused_donchian_hl_sweep(
                panel.close, panel.high, panel.low,
                np.asarray(g["window"]), cost=1e-3),
        ),
        "vwap": strat_case(
            "vwap_reversion",
            sweep.product_grid(
                k=jnp.linspace(0.5, 3.0, 20).astype(jnp.float32),
                window=jnp.arange(10, 50, 2, dtype=jnp.float32)),
            lambda g: fused.fused_vwap_sweep(
                panel.close, panel.volume, np.asarray(g["window"]),
                np.asarray(g["k"]), cost=1e-3),
        ),
        "stochastic": strat_case(
            "stochastic",
            sweep.product_grid(
                band=jnp.linspace(10.0, 40.0, 4).astype(jnp.float32),
                window=jnp.arange(5, 85, 2, dtype=jnp.float32)),
            lambda g: fused.fused_stochastic_sweep(
                panel.close, panel.high, panel.low,
                np.asarray(g["window"]), np.asarray(g["band"]), cost=1e-3),
        ),
        "keltner": strat_case(
            "keltner",
            sweep.product_grid(
                k=jnp.linspace(1.0, 3.0, 4).astype(jnp.float32),
                window=jnp.arange(5, 85, 2, dtype=jnp.float32)),
            lambda g: fused.fused_keltner_sweep(
                panel.close, panel.high, panel.low,
                np.asarray(g["window"]), np.asarray(g["k"]), cost=1e-3),
        ),
        "rsi": strat_case(
            "rsi",
            sweep.product_grid(
                period=jnp.arange(5, 45, 2, dtype=jnp.float32),
                band=jnp.linspace(10.0, 30.0, 4).astype(jnp.float32)),
            lambda g: fused.fused_rsi_sweep(
                panel.close, np.asarray(g["period"]), np.asarray(g["band"]),
                cost=1e-3),
        ),
        "macd": strat_case(
            "macd",
            sweep.product_grid(
                fast=jnp.arange(5, 13, dtype=jnp.float32),
                slow=jnp.arange(20, 52, 8, dtype=jnp.float32),
                signal=jnp.asarray([5.0, 9.0], jnp.float32)),
            lambda g: fused.fused_macd_sweep(
                panel.close, np.asarray(g["fast"]), np.asarray(g["slow"]),
                np.asarray(g["signal"]), cost=1e-3),
        ),
        "trix": strat_case(
            "trix",
            sweep.product_grid(
                span=jnp.arange(5, 45, 2, dtype=jnp.float32),
                signal=jnp.asarray([4.0, 9.0], jnp.float32)),
            lambda g: fused.fused_trix_sweep(
                panel.close, np.asarray(g["span"]), np.asarray(g["signal"]),
                cost=1e-3),
        ),
        "obv": strat_case(
            "obv_trend",
            sweep.product_grid(
                window=jnp.arange(5, 85, 2, dtype=jnp.float32)),
            lambda g: fused.fused_obv_sweep(
                panel.close, panel.volume, np.asarray(g["window"]),
                cost=1e-3),
        ),
        "pairs": (
            # Chunked generic reference: the unchunked vmap materializes the
            # whole (pairs, P, T) hysteresis-scan tree at once — several GB
            # at verify scale, which crashes/OOMs the chip.
            lambda: pairs.chunked_pairs_sweep(y_close, x_close, pgrid,
                                              param_chunk=40, cost=1e-3),
            lambda: fused.fused_pairs_sweep(
                y_close, x_close, np.asarray(pgrid["lookback"]),
                np.asarray(pgrid["z_entry"]), cost=1e-3),
        ),
    }
    # Per-kernel error budgets, asserted below: flip_rate caps with ~4x
    # headroom over the measured rates (r4: every kernel <= 0.05%, MACD
    # included after its generic path became the fused ladder's rounding
    # twin — demeaned close + ema_ladder, 26 -> 2 flips), so numeric
    # regressions FAIL the verify run loudly instead of drifting across
    # rounds. See DESIGN.md "Fused-kernel error budgets".
    FLIP_BUDGET = {"pairs": 0.002}
    FLIP_BUDGET_DEFAULT = 0.002
    ARGMAX_BUDGET = {"pairs": 1}      # knife-edge band entries, ~1 in 50
    ARGMAX_BUDGET_DEFAULT = 0

    over_budget = []
    for name, (run_ref, run_fused) in cases.items():
        ref = run_ref()
        got = run_fused()
        r = np.asarray(ref.sharpe)
        g = np.asarray(got.sharpe)
        rel = np.abs(g - r) / (np.abs(r) + 1e-6)
        # NaN-on-one-side cells would fail BOTH comparisons below and vanish
        # from the report — count them explicitly as mismatches.
        nan_mismatch = int((np.isnan(g) != np.isnan(r)).sum())
        rel = np.where(np.isnan(g) & np.isnan(r), 0.0, rel)
        # A "flip" = a materially different cell (a knife-edge crossover
        # resolved differently), vs float noise.
        flips = int((rel > 1e-2).sum()) + nan_mismatch
        argmax_flips = int((np.argmax(g, axis=1) != np.argmax(r, axis=1)).sum())
        out[name] = {
            "cells": int(rel.size),
            "max_rel_err_nonflip": float(rel[rel <= 1e-2].max())
            if (rel <= 1e-2).any() else None,
            "entry_flips": flips,
            "nan_mismatches": nan_mismatch,
            "flip_rate": flips / rel.size,
            "best_param_flips": argmax_flips,
            "n_tickers": int(r.shape[0]),
        }
        fb = FLIP_BUDGET.get(name, FLIP_BUDGET_DEFAULT)
        ab = ARGMAX_BUDGET.get(name, ARGMAX_BUDGET_DEFAULT)
        status = ""
        if flips / rel.size > fb or argmax_flips > ab:
            over_budget.append(name)
            status = (f"  OVER BUDGET (flip_rate cap {fb:.4f}, "
                      f"argmax cap {ab})")
        print(f"verify[{name}]: {flips}/{rel.size} entry flips "
              f"({nan_mismatch} NaN), {argmax_flips}/{r.shape[0]} "
              f"best-param flips{status}", file=sys.stderr)
    out["over_budget"] = over_budget
    print(json.dumps(out))
    if over_budget:
        sys.exit(f"bench --verify: kernels over their error budget: "
                 f"{', '.join(over_budget)} — a numeric regression, not "
                 "drift; see DESIGN.md 'Fused-kernel error budgets'")


if __name__ == "__main__":
    extra = [a for a in sys.argv[1:] if a != "--verify"]
    if extra:
        # An unrecognized flag (--help included) must NOT fall through to
        # the full 20-minute bench run.
        sys.exit(
            "usage: python bench.py [--verify]\n"
            "  (no flag)  full throughput bench; prints one JSON line\n"
            "  --verify   on-chip fused-vs-generic parity sweep\n"
            "config via env: DBX_BENCH_TICKERS/BARS/PARAMS/ITERS/WARMUP, "
            "DBX_BENCH_CONFIGS=name,name,...")
    if "--verify" in sys.argv[1:]:
        verify()
    else:
        main()
