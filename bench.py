"""Headline benchmark: (ticker x param) backtests/sec on one chip.

Workload = the BASELINE.json north star: a 500-ticker SMA-crossover sweep
over 5 years of daily bars with a 2,000-point (fast, slow) grid — 1,000,000
full backtests (indicators, positions, PnL, 9 summary metrics) per sweep
call, executed as a single fused jit kernel chunked over the param axis to
bound HBM.

Baseline: the reference's worker processes jobs serially at 1 job/sec (its
compute slot sleeps 1 s per job — reference ``src/worker/process.rs:23``), so
``vs_baseline`` is the raw speedup over 1 backtest/sec.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "backtests/sec", "vs_baseline": N}

Env overrides (for local smoke runs): DBX_BENCH_TICKERS, DBX_BENCH_BARS,
DBX_BENCH_PARAMS (grid points, must stay divisible by the chunk),
DBX_BENCH_CHUNK, DBX_BENCH_ITERS, DBX_BENCH_CPU=1 to force the CPU platform.
"""

import json
import os
import sys
import time


def main():
    if os.environ.get("DBX_BENCH_CPU") == "1":
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("DBX_BENCH_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    n_tickers = int(os.environ.get("DBX_BENCH_TICKERS", 500))
    n_bars = int(os.environ.get("DBX_BENCH_BARS", 1260))      # 5y daily
    n_params = int(os.environ.get("DBX_BENCH_PARAMS", 2000))
    chunk = int(os.environ.get("DBX_BENCH_CHUNK", 100))
    iters = int(os.environ.get("DBX_BENCH_ITERS", 10))

    dev = jax.devices()[0]
    print(f"bench: device={dev.device_kind} tickers={n_tickers} "
          f"bars={n_bars} params={n_params} chunk={chunk}", file=sys.stderr)

    # Param grid: n_fast x n_slow = n_params (default 20 x 100). Windows are
    # bar counts — keep them integral.
    n_fast = 20
    n_slow = n_params // n_fast
    grid = sweep.product_grid(
        fast=jnp.arange(5, 5 + n_fast, dtype=jnp.float32),
        slow=jnp.arange(30, 30 + 2 * n_slow, 2, dtype=jnp.float32))

    ohlcv = data.synthetic_ohlcv(n_tickers, n_bars, seed=0)
    panel = type(ohlcv)(*(jax.device_put(jnp.asarray(f), dev) for f in ohlcv))
    strategy = base.get_strategy("sma_crossover")

    if os.environ.get("DBX_BENCH_GENERIC") == "1":
        def run():
            return sweep.chunked_sweep(panel, strategy, grid,
                                       param_chunk=chunk, cost=1e-3)
    else:
        # Flagship path: the fused Pallas sweep kernel (ops/fused.py).
        from distributed_backtesting_exploration_tpu.ops import fused
        fa = np.asarray(grid["fast"])
        sl = np.asarray(grid["slow"])

        def run():
            return fused.fused_sma_sweep(panel.close, fa, sl, cost=1e-3)

    t0 = time.perf_counter()
    out = run()
    first_sharpe = np.asarray(out.sharpe)
    compile_s = time.perf_counter() - t0
    print(f"bench: first call (incl. compile) {compile_s:.1f}s", file=sys.stderr)

    # Chain every iteration into a device-side accumulator and fetch ONE
    # scalar at the end: the data dependency forces every sweep to execute
    # (with the remote-proxy TPU backend, block_until_ready alone can report
    # dispatch time), while paying the proxy round-trip only once.
    t0 = time.perf_counter()
    acc = jnp.float32(0.0)
    for _ in range(iters):
        out = run()
        acc = acc + jnp.sum(out.sharpe)
    acc_val = float(acc)   # the synchronizing fetch — must not be elided
    elapsed = time.perf_counter() - t0
    assert np.isfinite(acc_val)

    n_backtests = n_tickers * sweep.grid_size(grid)
    rate = n_backtests * iters / elapsed
    assert np.isfinite(first_sharpe).all()
    print(f"bench: {iters}x {n_backtests} backtests in {elapsed:.3f}s",
          file=sys.stderr)
    print(json.dumps({
        "metric": "backtests/sec/chip (ticker x param combos), "
                  "SMA-crossover sweep, 5y daily bars",
        "value": round(rate, 1),
        "unit": "backtests/sec",
        "vs_baseline": round(rate, 1),  # reference worker: 1 backtest/sec
    }))


if __name__ == "__main__":
    main()
