// Native worker shell: a C++ binary that owns the process and shells into
// the JAX engine through an embedded CPython interpreter — the C++ analogue
// of the north-star's "Rust shells into JAX via PyO3" (BASELINE.json), and
// the counterpart of the reference's native worker binary (reference
// src/worker/main.rs). The control loop, channels, and compute bridge live
// in distributed_backtesting_exploration_tpu.rpc.worker; this shell
// validates the native core (queue/decoder smoke), boots the interpreter,
// and runs the worker CLI with argv passed through.
//
// Build: see cpp/CMakeLists.txt (target dbx_worker_native). Run:
//   dbx_worker_native --connect localhost:50051 --backend jax

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "dbx_core.h"

#ifdef DBX_HAVE_PROTO
#include "backtesting.pb.h"
#endif

namespace {

#ifdef DBX_HAVE_PROTO
// The wire contract, exercised natively: build a JobSpec carrying a DBX1
// payload produced by the native codec, serialize, parse back, and check
// every field survives. Same .proto as the Python stubs — codegen parity
// with the reference's tonic-build step (reference build.rs:1-4).
bool proto_selftest() {
  const char csv[] =
      "open,high,low,close,volume\n"
      "1.0,2.0,0.5,1.5,100\n"
      "1.5,2.5,1.0,2.0,200\n";
  DbxOhlcv o;
  char err[128];
  if (dbx_csv_decode(csv, sizeof(csv) - 1, &o, err, sizeof(err)) != 0) {
    return false;
  }
  uint8_t* wire = nullptr;
  const size_t n = dbx_ohlcv_to_wire(&o, &wire);
  dbx_ohlcv_free(&o);
  if (n == 0) return false;

  dbx::rpc::JobSpec spec;
  spec.set_id("native-proto-selftest");
  spec.set_strategy("sma_crossover");
  spec.set_ohlcv(wire, n);
  spec.set_cost(0.001f);
  spec.set_periods_per_year(252);
  spec.set_wf_train(504);
  spec.set_wf_test(63);
  spec.set_wf_metric("sharpe");
  spec.set_top_k(16);
  spec.set_rank_metric("sortino");
  auto& fast = (*spec.mutable_grid())["fast"];
  fast.add_values(5.0f);
  fast.add_values(10.0f);
  std::string blob;
  const bool ser = spec.SerializeToString(&blob);

  dbx::rpc::JobSpec back;
  bool ok = ser && back.ParseFromString(blob) &&
            back.id() == "native-proto-selftest" &&
            back.strategy() == "sma_crossover" &&
            back.ohlcv().size() == n &&
            std::memcmp(back.ohlcv().data(), wire, n) == 0 &&
            back.grid().at("fast").values_size() == 2 &&
            back.grid().at("fast").values(1) == 10.0f &&
            back.periods_per_year() == 252 &&
            back.wf_train() == 504 && back.wf_test() == 63 &&
            back.wf_metric() == "sharpe" &&
            back.top_k() == 16 && back.rank_metric() == "sortino";
  dbx_bytes_free(wire);

  // And the payload decodes back through the native wire decoder.
  DbxOhlcv o2{};   // zero-init: freed below even when ok short-circuits
  ok = ok &&
       dbx_wire_decode(
           reinterpret_cast<const uint8_t*>(back.ohlcv().data()),
           back.ohlcv().size(), &o2, err, sizeof(err)) == 0 &&
       o2.n_bars == 2 && o2.close[1] == 2.0f;
  dbx_ohlcv_free(&o2);
  return ok;
}
#endif

// Pre-flight: exercise the native queue across threads and the CSV->wire
// decoder, so a broken core library fails fast and loudly here rather than
// mid-run inside a ctypes call.
bool selftest() {
  DbxQueue* q = dbx_queue_new(4);
  const char payload[] = "job-bytes";
  std::thread producer([q, &payload] {
    for (int i = 0; i < 8; ++i) {
      dbx_queue_push(q, reinterpret_cast<const uint8_t*>(payload),
                     sizeof(payload), -1);
    }
    dbx_queue_close(q);
  });
  int popped = 0;
  for (;;) {
    uint8_t* data = nullptr;
    size_t len = 0;
    const int rc = dbx_queue_pop(q, &data, &len, 1000);
    if (rc != 0) break;
    if (len != sizeof(payload) || std::memcmp(data, payload, len) != 0) {
      dbx_bytes_free(data);
      producer.join();
      dbx_queue_free(q);
      return false;
    }
    dbx_bytes_free(data);
    ++popped;
  }
  producer.join();
  dbx_queue_free(q);
  if (popped != 8) return false;

  const char csv[] =
      "open,high,low,close,volume\n"
      "1.0,2.0,0.5,1.5,100\n"
      "1.5,2.5,1.0,2.0,200\n";
  DbxOhlcv o;
  char err[128];
  if (dbx_csv_decode(csv, sizeof(csv) - 1, &o, err, sizeof(err)) != 0) {
    std::fprintf(stderr, "csv selftest: %s\n", err);
    return false;
  }
  uint8_t* wire = nullptr;
  const size_t n = dbx_ohlcv_to_wire(&o, &wire);
  DbxOhlcv o2{};   // zero-init: freed below even when decode is skipped
  const bool ok = n > 0 && dbx_wire_decode(wire, n, &o2, err, sizeof(err)) == 0
                  && o2.n_bars == 2 && o2.close[1] == 2.0f;
  dbx_bytes_free(wire);
  dbx_ohlcv_free(&o);
  dbx_ohlcv_free(&o2);
  return ok;
}

}  // namespace

int main(int argc, char** argv) {
  if (!selftest()) {
    std::fprintf(stderr, "dbx_worker_native: core selftest FAILED\n");
    return 2;
  }
  std::fprintf(stderr, "dbx_worker_native: core selftest ok\n");
#ifdef DBX_HAVE_PROTO
  if (!proto_selftest()) {
    std::fprintf(stderr, "dbx_worker_native: proto selftest FAILED\n");
    return 2;
  }
  std::fprintf(stderr, "dbx_worker_native: proto selftest ok\n");
#else
  std::fprintf(stderr, "dbx_worker_native: proto selftest skipped "
                       "(built without libprotobuf)\n");
#endif

  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  // argv is for the worker CLI, not the interpreter: without parse_argv=0
  // Python would swallow flags like --help itself.
  config.parse_argv = 0;
  PyStatus status = PyConfig_SetBytesArgv(&config, argc, argv);
  if (PyStatus_Exception(status)) {
    std::fprintf(stderr, "dbx_worker_native: argv setup failed\n");
    return 2;
  }
  status = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(status)) {
    std::fprintf(stderr, "dbx_worker_native: interpreter init failed\n");
    return 2;
  }

  const char* boot =
      "import sys\n"
      "from distributed_backtesting_exploration_tpu.rpc import worker\n"
      "worker.main(sys.argv[1:])\n";
  const int rc = PyRun_SimpleString(boot);
  if (Py_FinalizeEx() < 0) return 120;
  return rc == 0 ? 0 : 1;
}
