// C ABI of the native runtime core (libdbx_core.so).
//
// Native-parity layer: the reference implements its entire runtime natively
// (Rust: dispatcher state + pruning thread, worker poll loop, flume channel
// substrate, CSV file handling — reference src/server/main.rs,
// src/worker/main.rs). This environment has no Rust toolchain, so the native
// runtime substrate is C++ (SURVEY.md §2.2), exposed through a plain C ABI
// consumed from Python via ctypes (no pybind11 in the image) and from the
// native worker shell (worker_native.cc).
//
// Components:
//   - OHLCV CSV decoder: the data-loader hot path. Parses header-mapped CSV
//     bytes straight into column-major float32 arrays (and to the DBX1 wire
//     block) with no Python-level parsing.
//   - Bounded MPMC blob queue: the channel substrate bridging I/O and
//     compute threads (the role flume bounded channels play in the
//     reference worker, reference src/worker/main.rs:32-42).
//   - Peer registry: liveness map with last-seen stamping and windowed
//     pruning (the reference server's dedicated pruning thread, reference
//     src/server/main.rs:39-52).

#ifndef DBX_CORE_H_
#define DBX_CORE_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------------------
// OHLCV decode
// ---------------------------------------------------------------------------

// Column-major single-ticker OHLCV block; arrays are malloc'd, length n_bars.
typedef struct {
  uint32_t n_bars;
  float* open;
  float* high;
  float* low;
  float* close;
  float* volume;
} DbxOhlcv;

// Parse CSV bytes (header row naming open/high/low/close/volume in any
// column order, extra columns ignored). Returns 0 on success; nonzero on
// error with a message in err (NUL-terminated, truncated to errlen).
int dbx_csv_decode(const char* data, size_t len, DbxOhlcv* out, char* err,
                   size_t errlen);

// Encode an OHLCV block into the DBX1 wire format ("DBX1" u32-LE T then five
// f32[T] fields). *out is malloc'd; returns its byte length, or 0 on error.
size_t dbx_ohlcv_to_wire(const DbxOhlcv* o, uint8_t** out);

// Parse a DBX1 wire block. Returns 0 on success.
int dbx_wire_decode(const uint8_t* data, size_t len, DbxOhlcv* out, char* err,
                    size_t errlen);

void dbx_ohlcv_free(DbxOhlcv* o);
void dbx_bytes_free(uint8_t* p);

// ---------------------------------------------------------------------------
// Bounded MPMC blob queue
// ---------------------------------------------------------------------------

typedef struct DbxQueue DbxQueue;

DbxQueue* dbx_queue_new(size_t capacity);
// Push a copy of data. Blocks up to timeout_ms when full (-1 = forever).
// Returns 0 ok, 1 timeout, 2 closed.
int dbx_queue_push(DbxQueue* q, const uint8_t* data, size_t len,
                   int64_t timeout_ms);
// Push to the FRONT of the queue (next pop returns it) — the dispatcher's
// requeue-expired-lease path, which must re-dispatch recovered jobs before
// fresh ones. Same blocking/return contract as dbx_queue_push.
int dbx_queue_push_front(DbxQueue* q, const uint8_t* data, size_t len,
                         int64_t timeout_ms);
// Pop into a malloc'd buffer (*data, *len). Blocks up to timeout_ms when
// empty. Returns 0 ok, 1 timeout, 2 closed-and-drained.
int dbx_queue_pop(DbxQueue* q, uint8_t** data, size_t* len,
                  int64_t timeout_ms);
// Close: pushes fail immediately; pops drain remaining items then report
// closed.
void dbx_queue_close(DbxQueue* q);
size_t dbx_queue_size(DbxQueue* q);
void dbx_queue_free(DbxQueue* q);

// ---------------------------------------------------------------------------
// Job-queue state machine
// ---------------------------------------------------------------------------
//
// The dispatcher's lease/tombstone/completion transitions (the part of the
// reference's dispatcher state that is native there — its whole Dispatcher
// struct lives in Rust, reference src/server/main.rs:20-190). gRPC serving
// stays in Python (no grpc++ in this environment); this owns the id-state
// hot path behind it: pending FIFO, tombstone skip, lease table, completion
// idempotency, expiry/prune requeue. Semantics mirror the Python fallback in
// rpc/dispatcher.py byte for byte; the mid-take completion race is modeled
// by the explicit take_begin/take_commit split (payload materialization
// happens between the two, outside any lock).
//
// Job ids are NUL-terminated strings up to DBX_JOBQ_MAX_ID bytes.

#define DBX_JOBQ_MAX_ID 511

// Id/peer callback (also used by the registry's prune below).
typedef void (*DbxPrunedFn)(const char* peer_id, void* ctx);

typedef struct DbxJobQueue DbxJobQueue;

typedef struct {
  int64_t pending;      // live FIFO entries (tombstones excluded)
  int64_t leased;
  int64_t completed;
  int64_t requeued;
  int64_t failed;
  double combos_done;   // sum of combo credits over first completions
} DbxJobqStats;

DbxJobQueue* dbx_jobq_new(void);
void dbx_jobq_free(DbxJobQueue* q);
// Register a job id with its combo-count credit (recorded on first
// completion). Idempotent; required before any other call names the id.
// Returns 0, or 1 if the id exceeds DBX_JOBQ_MAX_ID bytes.
int dbx_jobq_register(DbxJobQueue* q, const char* id, double combos);
// Append a registered id to the pending FIFO.
void dbx_jobq_push_pending(DbxJobQueue* q, const char* id);
// Journal-restore helpers: mark terminal states without crediting
// combos_done (a restored completion's work happened in a previous run).
void dbx_jobq_mark_completed(DbxJobQueue* q, const char* id);
void dbx_jobq_mark_failed(DbxJobQueue* q, const char* id);
// Pop the next live pending id (skipping + clearing tombstones) into out.
// Returns 1 with an id written, 0 when the FIFO is empty, -1 when the next
// id does not fit in cap bytes (the id is returned to the front of the
// FIFO; pass a buffer of DBX_JOBQ_MAX_ID + 1 bytes to make this
// unreachable).
int dbx_jobq_take_begin(DbxJobQueue* q, char* out, size_t cap);
// Lease a popped id to worker for lease_ms. Returns 0 leased; 1 when the
// job completed in the take window (tombstone cleared, not leased).
int dbx_jobq_take_commit(DbxJobQueue* q, const char* id, const char* worker,
                         int64_t lease_ms);
// Mark a popped id failed (unreadable payload). Returns 0 marked; 1 when
// the job completed in the take window (not marked).
int dbx_jobq_fail(DbxJobQueue* q, const char* id);
// Record a completion. Returns 0 new, 1 duplicate, 2 unknown id. Always
// clears any lease; a completion for an id still in the FIFO installs a
// tombstone so take skips it.
int dbx_jobq_complete(DbxJobQueue* q, const char* id);

// Batched transitions: one library crossing per RPC instead of one per
// job, moving int32 HANDLES instead of strings. Every id registers once
// and gets a dense index in registration order (the caller mirrors the
// same order, so both sides agree without the index ever crossing at
// registration); a batch-32 take/commit/complete then carries one
// 128-byte int32 array per crossing. The string-keyed batch surface
// measured SLOWER than the Python dict fallback — per-id string
// marshalling, not the transitions, was the cost.
//
// Register + push n ids in one crossing (ids packed at a caller-chosen
// `stride` bytes per NUL-terminated id; combo credits parallel to the id
// slots). Ids longer than DBX_JOBQ_MAX_ID are skipped; returns the
// number accepted (callers enforce the cap beforehand, so a skip is a
// contract violation surfacing as a short count, never silent state
// corruption).
int dbx_jobq_enqueue_n(DbxJobQueue* q, const char* ids, int stride,
                       const double* combos, int n);
// Pop up to n live pending ids' indices into out. Returns the count
// popped (0 when the FIFO is empty).
int dbx_jobq_take_begin_idx_n(DbxJobQueue* q, int32_t* out, int n);
// Lease n popped indices to worker in one crossing; committed[i] = 1
// leased, 0 completed-in-the-take-window (dropped, orphan tombstone
// cleared — dbx_jobq_take_commit's per-id semantics). Returns the number
// leased.
int dbx_jobq_take_commit_idx_n(DbxJobQueue* q, const int32_t* idxs, int n,
                               const char* worker, int64_t lease_ms,
                               uint8_t* committed);
// Record n completions in one crossing; outcomes[i] = 0 new, 1 dup,
// 2 unknown (dbx_jobq_complete's per-id semantics; a negative or
// out-of-range index is unknown — the caller maps unseen RPC ids to -1).
void dbx_jobq_complete_idx_n(DbxJobQueue* q, const int32_t* idxs, int n,
                             uint8_t* outcomes);
// Requeue jobs whose lease deadline passed (front of the FIFO, in lease
// order — matching the Python fallback's insertion-ordered scan). The
// callback receives each requeued id. Returns the count.
int dbx_jobq_requeue_expired(DbxJobQueue* q, DbxPrunedFn fn, void* ctx);
// Requeue every job leased to worker (front of the FIFO, lease order).
int dbx_jobq_requeue_worker(DbxJobQueue* q, const char* worker, DbxPrunedFn fn,
                            void* ctx);
void dbx_jobq_stats(DbxJobQueue* q, DbxJobqStats* out);
// 1 when no live pending entries and no leases remain.
int dbx_jobq_drained(DbxJobQueue* q);

// ---------------------------------------------------------------------------
// Peer registry
// ---------------------------------------------------------------------------

typedef struct DbxRegistry DbxRegistry;

DbxRegistry* dbx_registry_new(int64_t prune_window_ms);
// Stamp a peer as alive now. Returns 1 if newly registered, 0 if refreshed.
int dbx_registry_touch(DbxRegistry* r, const char* peer_id);
// Remove peers silent past the window. For each removed peer the callback
// (DbxPrunedFn, declared above) is invoked with its id. Returns the number
// pruned.
int dbx_registry_prune(DbxRegistry* r, DbxPrunedFn fn, void* ctx);
int dbx_registry_alive(DbxRegistry* r);
void dbx_registry_free(DbxRegistry* r);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // DBX_CORE_H_
