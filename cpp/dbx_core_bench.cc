// Microbench: the DbxJobQueue state machine driven through the C ABI with
// no foreign-function crossing — the grain a native dispatcher shell pays
// (the reference's whole dispatcher state is native Rust, reference
// src/server/main.rs:20-190). Complements bench.py's `queue_machine`
// config, which measures the same cycle driven from Python over ctypes:
// there the CPython dict fallback wins (zero marshalling), which is why
// the Python-driven default substrate is python; HERE the native machine
// is the only substrate and this records its headroom.
//
// Cycle per batch of 32 (mirrors JobQueue.take/complete_batch):
//   enqueue_n -> take_begin_idx_n -> take_commit_idx_n -> complete_idx_n
//
// Output: one line, "<jobs> jobs in <s> s -> <jobs/s> jobs/s".

#include "dbx_core.h"

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

int main(int argc, char** argv) {
  const int n_jobs = argc > 1 ? std::atoi(argv[1]) : 200000;
  const int batch = 32;

  // Pre-build the NUL-separated id pack per batch (uuid-sized ids).
  std::vector<std::string> packs;
  std::vector<std::vector<double>> combo_batches;
  for (int base = 0; base < n_jobs; base += batch) {
    std::string pack;
    std::vector<double> combos;
    for (int i = base; i < base + batch && i < n_jobs; ++i) {
      char id[64];
      std::snprintf(id, sizeof id, "job-%08x-%08x", i, i * 2654435761u);
      pack.append(id);
      pack.push_back('\0');
      combos.push_back(40.0);
    }
    packs.push_back(std::move(pack));
    combo_batches.push_back(std::move(combos));
  }

  DbxJobQueue* q = dbx_jobq_new();
  int32_t idxs[batch];
  uint8_t flags[batch];

  const auto t0 = std::chrono::steady_clock::now();
  for (size_t b = 0; b < packs.size(); ++b) {
    const int n = static_cast<int>(combo_batches[b].size());
    dbx_jobq_enqueue_n(q, packs[b].data(), 0, combo_batches[b].data(), n);
  }
  int done = 0;
  for (;;) {
    const int got = dbx_jobq_take_begin_idx_n(q, idxs, batch);
    if (got == 0) break;
    dbx_jobq_take_commit_idx_n(q, idxs, got, "w", 60000, flags);
    dbx_jobq_complete_idx_n(q, idxs, got, flags);
    done += got;
  }
  const double s = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - t0)
                       .count();

  DbxJobqStats st;
  dbx_jobq_stats(q, &st);
  if (done != n_jobs || st.completed != n_jobs || !dbx_jobq_drained(q)) {
    std::fprintf(stderr, "FAIL: done=%d completed=%lld drained=%d\n", done,
                 static_cast<long long>(st.completed), dbx_jobq_drained(q));
    dbx_jobq_free(q);
    return 1;
  }
  dbx_jobq_free(q);
  std::printf("%d jobs in %.4f s -> %.0f jobs/s\n", n_jobs, s, n_jobs / s);
  return 0;
}
