// Native runtime core implementation. See dbx_core.h for the component map.

#include "dbx_core.h"

#include <algorithm>
#include <cctype>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

void set_err(char* err, size_t errlen, const char* msg) {
  if (err && errlen) {
    std::snprintf(err, errlen, "%s", msg);
  }
}

// Fast float parse over [p, end); advances p past the number. Falls back to
// strtod semantics via manual exponent handling — CSV numeric fields only.
bool parse_float(const char*& p, const char* end, float* out) {
  const char* start = p;
  // strtof needs a NUL-terminated buffer; copy the token (fields are short).
  char buf[64];
  size_t n = 0;
  while (p < end && *p != ',' && *p != '\n' && *p != '\r' &&
         n < sizeof(buf) - 1) {
    buf[n++] = *p++;
  }
  buf[n] = '\0';
  if (n == 0) return false;
  char* stop = nullptr;
  *out = std::strtof(buf, &stop);
  return stop == buf + n && p >= start;
}

}  // namespace

// ---------------------------------------------------------------------------
// CSV decode
// ---------------------------------------------------------------------------

extern "C" int dbx_csv_decode(const char* data, size_t len, DbxOhlcv* out,
                              char* err, size_t errlen) {
  std::memset(out, 0, sizeof(*out));
  const char* p = data;
  const char* end = data + len;
  if (p == end) {
    set_err(err, errlen, "empty CSV payload");
    return 1;
  }

  // Header row: map column index -> field slot (0..4), -1 = ignore.
  std::vector<int> slots;
  int found = 0;
  {
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    const char* q = p;
    while (q < line_end) {
      const char* tok = q;
      while (q < line_end && *q != ',') ++q;
      std::string name(tok, q - tok);
      while (!name.empty() && (name.back() == '\r' || name.back() == ' '))
        name.pop_back();
      size_t h = 0;
      while (h < name.size() && name[h] == ' ') ++h;
      name = name.substr(h);
      for (auto& c : name) c = static_cast<char>(std::tolower(c));
      int slot = -1;
      if (name == "open") slot = 0;
      else if (name == "high") slot = 1;
      else if (name == "low") slot = 2;
      else if (name == "close") slot = 3;
      else if (name == "volume") slot = 4;
      if (slot >= 0) ++found;
      slots.push_back(slot);
      if (q < line_end) ++q;  // skip comma
    }
    p = line_end < end ? line_end + 1 : end;
  }
  if (found < 5) {
    set_err(err, errlen, "CSV header missing open/high/low/close/volume");
    return 1;
  }

  std::vector<float> cols[5];
  while (p < end) {
    // Skip blank lines.
    if (*p == '\n' || *p == '\r') {
      ++p;
      continue;
    }
    size_t col = 0;
    float row[5];
    bool row_ok = true;
    bool have[5] = {false, false, false, false, false};
    while (p <= end) {
      int slot = col < slots.size() ? slots[col] : -1;
      if (slot >= 0) {
        float v;
        if (!parse_float(p, end, &v)) {
          row_ok = false;
          break;
        }
        row[slot] = v;
        have[slot] = true;
      } else {
        while (p < end && *p != ',' && *p != '\n') ++p;
      }
      ++col;
      if (p >= end || *p == '\n' || *p == '\r') break;
      if (*p == ',') ++p;
    }
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
    if (!row_ok || !(have[0] && have[1] && have[2] && have[3] && have[4])) {
      set_err(err, errlen, "malformed CSV data row");
      return 1;
    }
    for (int i = 0; i < 5; ++i) cols[i].push_back(row[i]);
  }
  if (cols[0].empty()) {
    set_err(err, errlen, "CSV has no data rows");
    return 1;
  }

  uint32_t n = static_cast<uint32_t>(cols[0].size());
  float* bufs[5];
  for (int i = 0; i < 5; ++i) {
    bufs[i] = static_cast<float*>(std::malloc(sizeof(float) * n));
    std::memcpy(bufs[i], cols[i].data(), sizeof(float) * n);
  }
  out->n_bars = n;
  out->open = bufs[0];
  out->high = bufs[1];
  out->low = bufs[2];
  out->close = bufs[3];
  out->volume = bufs[4];
  return 0;
}

extern "C" size_t dbx_ohlcv_to_wire(const DbxOhlcv* o, uint8_t** out) {
  if (!o || !o->n_bars) return 0;
  const uint32_t n = o->n_bars;
  const size_t total = 8 + sizeof(float) * 5 * n;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(total));
  if (!buf) return 0;
  std::memcpy(buf, "DBX1", 4);
  std::memcpy(buf + 4, &n, 4);  // little-endian hosts only (x86/ARM)
  const float* fields[5] = {o->open, o->high, o->low, o->close, o->volume};
  size_t off = 8;
  for (const float* f : fields) {
    std::memcpy(buf + off, f, sizeof(float) * n);
    off += sizeof(float) * n;
  }
  *out = buf;
  return total;
}

extern "C" int dbx_wire_decode(const uint8_t* data, size_t len, DbxOhlcv* out,
                               char* err, size_t errlen) {
  std::memset(out, 0, sizeof(*out));
  if (len < 8 || std::memcmp(data, "DBX1", 4) != 0) {
    set_err(err, errlen, "bad magic; not a DBX1 block");
    return 1;
  }
  uint32_t n;
  std::memcpy(&n, data + 4, 4);
  const size_t need = 8 + sizeof(float) * 5 * static_cast<size_t>(n);
  if (len < need) {
    set_err(err, errlen, "truncated DBX1 block");
    return 1;
  }
  float* bufs[5];
  size_t off = 8;
  for (int i = 0; i < 5; ++i) {
    bufs[i] = static_cast<float*>(std::malloc(sizeof(float) * n));
    std::memcpy(bufs[i], data + off, sizeof(float) * n);
    off += sizeof(float) * n;
  }
  out->n_bars = n;
  out->open = bufs[0];
  out->high = bufs[1];
  out->low = bufs[2];
  out->close = bufs[3];
  out->volume = bufs[4];
  return 0;
}

extern "C" void dbx_ohlcv_free(DbxOhlcv* o) {
  if (!o) return;
  std::free(o->open);
  std::free(o->high);
  std::free(o->low);
  std::free(o->close);
  std::free(o->volume);
  std::memset(o, 0, sizeof(*o));
}

extern "C" void dbx_bytes_free(uint8_t* p) { std::free(p); }

// ---------------------------------------------------------------------------
// Bounded MPMC blob queue
// ---------------------------------------------------------------------------

struct DbxQueue {
  explicit DbxQueue(size_t cap) : capacity(cap) {}
  const size_t capacity;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::deque<std::vector<uint8_t>> items;
  bool closed = false;
};

extern "C" DbxQueue* dbx_queue_new(size_t capacity) {
  return new DbxQueue(capacity ? capacity : 1);
}

static bool wait_on(std::condition_variable& cv,
                    std::unique_lock<std::mutex>& lk, int64_t timeout_ms,
                    const std::function<bool()>& pred) {
  if (timeout_ms < 0) {
    cv.wait(lk, pred);
    return true;
  }
  return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
}

extern "C" int dbx_queue_push(DbxQueue* q, const uint8_t* data, size_t len,
                              int64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(q->mu);
  const bool ok = wait_on(q->not_full, lk, timeout_ms, [q] {
    return q->closed || q->items.size() < q->capacity;
  });
  if (!ok) return 1;
  if (q->closed) return 2;
  q->items.emplace_back(data, data + len);
  q->not_empty.notify_one();
  return 0;
}

extern "C" int dbx_queue_push_front(DbxQueue* q, const uint8_t* data,
                                    size_t len, int64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(q->mu);
  const bool ok = wait_on(q->not_full, lk, timeout_ms, [q] {
    return q->closed || q->items.size() < q->capacity;
  });
  if (!ok) return 1;
  if (q->closed) return 2;
  q->items.emplace_front(data, data + len);
  q->not_empty.notify_one();
  return 0;
}

extern "C" int dbx_queue_pop(DbxQueue* q, uint8_t** data, size_t* len,
                             int64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(q->mu);
  const bool ok = wait_on(q->not_empty, lk, timeout_ms,
                          [q] { return q->closed || !q->items.empty(); });
  if (!ok) return 1;
  if (q->items.empty()) return 2;  // closed and drained
  std::vector<uint8_t> item = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  lk.unlock();
  *len = item.size();
  *data = static_cast<uint8_t*>(std::malloc(item.size() ? item.size() : 1));
  std::memcpy(*data, item.data(), item.size());
  return 0;
}

extern "C" void dbx_queue_close(DbxQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

extern "C" size_t dbx_queue_size(DbxQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

extern "C" void dbx_queue_free(DbxQueue* q) { delete q; }

// ---------------------------------------------------------------------------
// Job-queue state machine
// ---------------------------------------------------------------------------
//
// Mirrors rpc/dispatcher.py's Python fallback exactly; see dbx_core.h for
// the transition contract and the take_begin/take_commit race model.

struct DbxJobQueue {
  struct Lease {
    std::string worker;
    std::chrono::steady_clock::time_point deadline;
    uint64_t seq;  // insertion order, so requeue scans match the Python
                   // fallback's insertion-ordered dict iteration
  };
  std::mutex mu;
  std::deque<std::string> pending;
  std::unordered_set<std::string> tombstones;
  std::unordered_map<std::string, double> records;  // id -> combo credit
  std::unordered_map<std::string, Lease> leases;
  std::unordered_map<std::string, double> completed;
  std::unordered_set<std::string> failed;
  uint64_t lease_seq = 0;
  int64_t requeued = 0;
  double combos_done = 0.0;
};

extern "C" DbxJobQueue* dbx_jobq_new(void) { return new DbxJobQueue(); }

extern "C" void dbx_jobq_free(DbxJobQueue* q) { delete q; }

extern "C" int dbx_jobq_register(DbxJobQueue* q, const char* id,
                                 double combos) {
  if (std::strlen(id) > DBX_JOBQ_MAX_ID) return 1;
  std::lock_guard<std::mutex> lk(q->mu);
  q->records[id] = combos;
  return 0;
}

extern "C" void dbx_jobq_push_pending(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  q->pending.emplace_back(id);
}

extern "C" void dbx_jobq_mark_completed(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  q->completed.emplace(id, 0.0);  // no combos_done credit: prior run's work
}

extern "C" void dbx_jobq_mark_failed(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  q->failed.insert(id);
}

extern "C" int dbx_jobq_take_begin(DbxJobQueue* q, char* out, size_t cap) {
  std::lock_guard<std::mutex> lk(q->mu);
  while (!q->pending.empty()) {
    std::string id = std::move(q->pending.front());
    q->pending.pop_front();
    if (q->tombstones.erase(id)) continue;  // completed while pending
    if (id.size() + 1 > cap) {
      // Caller's buffer cannot hold the id (register caps ids at
      // DBX_JOBQ_MAX_ID, so a >=512-byte buffer never hits this). Put the
      // id back and report the contract violation — silently dropping a
      // popped job would drain the queue with work unprocessed.
      q->pending.emplace_front(std::move(id));
      return -1;
    }
    std::memcpy(out, id.c_str(), id.size() + 1);
    return 1;
  }
  return 0;
}

extern "C" int dbx_jobq_take_commit(DbxJobQueue* q, const char* id,
                                    const char* worker, int64_t lease_ms) {
  std::lock_guard<std::mutex> lk(q->mu);
  if (q->completed.count(id)) {
    // Completed in the unlocked take window: drop the orphan tombstone the
    // completion installed, and do not lease.
    q->tombstones.erase(id);
    return 1;
  }
  q->leases[id] = DbxJobQueue::Lease{
      worker,
      std::chrono::steady_clock::now() + std::chrono::milliseconds(lease_ms),
      q->lease_seq++};
  return 0;
}

extern "C" int dbx_jobq_fail(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  if (q->completed.count(id)) {
    q->tombstones.erase(id);
    return 1;
  }
  q->failed.insert(id);
  return 0;
}

extern "C" int dbx_jobq_complete(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  auto rec = q->records.find(id);
  if (rec == q->records.end()) return 2;
  const bool had_lease = q->leases.erase(id) > 0;
  if (q->completed.count(id)) return 1;
  if (!had_lease && !q->failed.count(id) && !q->tombstones.count(id)) {
    // Completion for a job still sitting in the pending FIFO (late RPC
    // straddling a lease expiry or restart): no interior removal, so
    // tombstone the id for take to skip.
    q->tombstones.insert(id);
  }
  q->completed[id] = rec->second;
  q->combos_done += rec->second;
  return 0;
}

namespace {

int requeue_matching(
    DbxJobQueue* q, DbxPrunedFn fn, void* ctx,
    const std::function<bool(const DbxJobQueue::Lease&)>& match) {
  std::vector<std::pair<uint64_t, std::string>> hit;
  {
    std::lock_guard<std::mutex> lk(q->mu);
    for (const auto& [id, lease] : q->leases) {
      if (match(lease)) hit.emplace_back(lease.seq, id);
    }
    // Lease-insertion order, so the front-of-queue result is identical to
    // the Python fallback's insertion-ordered scan + appendleft loop.
    std::sort(hit.begin(), hit.end());
    for (const auto& [seq, id] : hit) {
      (void)seq;
      q->leases.erase(id);
      q->pending.emplace_front(id);
    }
    q->requeued += static_cast<int64_t>(hit.size());
  }
  if (fn) {
    for (const auto& [seq, id] : hit) {
      (void)seq;
      fn(id.c_str(), ctx);
    }
  }
  return static_cast<int>(hit.size());
}

}  // namespace

extern "C" int dbx_jobq_requeue_expired(DbxJobQueue* q, DbxPrunedFn fn,
                                        void* ctx) {
  const auto now = std::chrono::steady_clock::now();
  return requeue_matching(
      q, fn, ctx,
      [now](const DbxJobQueue::Lease& l) { return l.deadline <= now; });
}

extern "C" int dbx_jobq_requeue_worker(DbxJobQueue* q, const char* worker,
                                       DbxPrunedFn fn, void* ctx) {
  const std::string w = worker;
  return requeue_matching(
      q, fn, ctx, [&w](const DbxJobQueue::Lease& l) { return l.worker == w; });
}

extern "C" void dbx_jobq_stats(DbxJobQueue* q, DbxJobqStats* out) {
  std::lock_guard<std::mutex> lk(q->mu);
  out->pending = static_cast<int64_t>(q->pending.size()) -
                 static_cast<int64_t>(q->tombstones.size());
  out->leased = static_cast<int64_t>(q->leases.size());
  out->completed = static_cast<int64_t>(q->completed.size());
  out->requeued = q->requeued;
  out->failed = static_cast<int64_t>(q->failed.size());
  out->combos_done = q->combos_done;
}

extern "C" int dbx_jobq_drained(DbxJobQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  const int64_t live = static_cast<int64_t>(q->pending.size()) -
                       static_cast<int64_t>(q->tombstones.size());
  return (live == 0 && q->leases.empty()) ? 1 : 0;
}

// ---------------------------------------------------------------------------
// Peer registry
// ---------------------------------------------------------------------------

struct DbxRegistry {
  explicit DbxRegistry(int64_t window) : window_ms(window) {}
  const int64_t window_ms;
  std::mutex mu;
  std::unordered_map<std::string, std::chrono::steady_clock::time_point> peers;
};

extern "C" DbxRegistry* dbx_registry_new(int64_t prune_window_ms) {
  return new DbxRegistry(prune_window_ms);
}

extern "C" int dbx_registry_touch(DbxRegistry* r, const char* peer_id) {
  std::lock_guard<std::mutex> lk(r->mu);
  auto now = std::chrono::steady_clock::now();
  auto [it, inserted] = r->peers.insert_or_assign(peer_id, now);
  (void)it;
  return inserted ? 1 : 0;
}

extern "C" int dbx_registry_prune(DbxRegistry* r, DbxPrunedFn fn, void* ctx) {
  std::vector<std::string> dead;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    const auto cutoff = std::chrono::steady_clock::now() -
                        std::chrono::milliseconds(r->window_ms);
    for (auto it = r->peers.begin(); it != r->peers.end();) {
      if (it->second < cutoff) {
        dead.push_back(it->first);
        it = r->peers.erase(it);
      } else {
        ++it;
      }
    }
  }
  if (fn) {
    for (const auto& id : dead) fn(id.c_str(), ctx);
  }
  return static_cast<int>(dead.size());
}

extern "C" int dbx_registry_alive(DbxRegistry* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  return static_cast<int>(r->peers.size());
}

extern "C" void dbx_registry_free(DbxRegistry* r) { delete r; }
