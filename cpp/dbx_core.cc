// Native runtime core implementation. See dbx_core.h for the component map.

#include "dbx_core.h"

#include <algorithm>
#include <cctype>
#include <chrono>
#include <cmath>
#include <limits>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

void set_err(char* err, size_t errlen, const char* msg) {
  if (err && errlen) {
    std::snprintf(err, errlen, "%s", msg);
  }
}

// Fast float parse over [p, end); advances p past the number. Falls back to
// strtod semantics via manual exponent handling — CSV numeric fields only.
bool parse_float(const char*& p, const char* end, float* out) {
  const char* start = p;
  // strtof needs a NUL-terminated buffer; copy the token (fields are short).
  char buf[64];
  size_t n = 0;
  while (p < end && *p != ',' && *p != '\n' && *p != '\r' &&
         n < sizeof(buf) - 1) {
    buf[n++] = *p++;
  }
  buf[n] = '\0';
  if (n == 0) return false;
  char* stop = nullptr;
  *out = std::strtof(buf, &stop);
  return stop == buf + n && p >= start;
}

}  // namespace

// ---------------------------------------------------------------------------
// CSV decode
// ---------------------------------------------------------------------------

extern "C" int dbx_csv_decode(const char* data, size_t len, DbxOhlcv* out,
                              char* err, size_t errlen) {
  std::memset(out, 0, sizeof(*out));
  const char* p = data;
  const char* end = data + len;
  if (p == end) {
    set_err(err, errlen, "empty CSV payload");
    return 1;
  }

  // Header row: map column index -> field slot (0..4), -1 = ignore.
  std::vector<int> slots;
  int found = 0;
  {
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    const char* q = p;
    while (q < line_end) {
      const char* tok = q;
      while (q < line_end && *q != ',') ++q;
      std::string name(tok, q - tok);
      while (!name.empty() && (name.back() == '\r' || name.back() == ' '))
        name.pop_back();
      size_t h = 0;
      while (h < name.size() && name[h] == ' ') ++h;
      name = name.substr(h);
      for (auto& c : name) c = static_cast<char>(std::tolower(c));
      int slot = -1;
      if (name == "open") slot = 0;
      else if (name == "high") slot = 1;
      else if (name == "low") slot = 2;
      else if (name == "close") slot = 3;
      else if (name == "volume") slot = 4;
      if (slot >= 0) ++found;
      slots.push_back(slot);
      if (q < line_end) ++q;  // skip comma
    }
    p = line_end < end ? line_end + 1 : end;
  }
  if (found < 5) {
    set_err(err, errlen, "CSV header missing open/high/low/close/volume");
    return 1;
  }

  std::vector<float> cols[5];
  while (p < end) {
    // Skip blank lines.
    if (*p == '\n' || *p == '\r') {
      ++p;
      continue;
    }
    size_t col = 0;
    float row[5];
    bool row_ok = true;
    bool have[5] = {false, false, false, false, false};
    while (p <= end) {
      int slot = col < slots.size() ? slots[col] : -1;
      if (slot >= 0) {
        float v;
        if (!parse_float(p, end, &v)) {
          row_ok = false;
          break;
        }
        row[slot] = v;
        have[slot] = true;
      } else {
        while (p < end && *p != ',' && *p != '\n') ++p;
      }
      ++col;
      if (p >= end || *p == '\n' || *p == '\r') break;
      if (*p == ',') ++p;
    }
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
    if (!row_ok || !(have[0] && have[1] && have[2] && have[3] && have[4])) {
      set_err(err, errlen, "malformed CSV data row");
      return 1;
    }
    for (int i = 0; i < 5; ++i) cols[i].push_back(row[i]);
  }
  if (cols[0].empty()) {
    set_err(err, errlen, "CSV has no data rows");
    return 1;
  }

  uint32_t n = static_cast<uint32_t>(cols[0].size());
  float* bufs[5];
  for (int i = 0; i < 5; ++i) {
    bufs[i] = static_cast<float*>(std::malloc(sizeof(float) * n));
    std::memcpy(bufs[i], cols[i].data(), sizeof(float) * n);
  }
  out->n_bars = n;
  out->open = bufs[0];
  out->high = bufs[1];
  out->low = bufs[2];
  out->close = bufs[3];
  out->volume = bufs[4];
  return 0;
}

extern "C" size_t dbx_ohlcv_to_wire(const DbxOhlcv* o, uint8_t** out) {
  if (!o || !o->n_bars) return 0;
  const uint32_t n = o->n_bars;
  const size_t total = 8 + sizeof(float) * 5 * n;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(total));
  if (!buf) return 0;
  std::memcpy(buf, "DBX1", 4);
  std::memcpy(buf + 4, &n, 4);  // little-endian hosts only (x86/ARM)
  const float* fields[5] = {o->open, o->high, o->low, o->close, o->volume};
  size_t off = 8;
  for (const float* f : fields) {
    std::memcpy(buf + off, f, sizeof(float) * n);
    off += sizeof(float) * n;
  }
  *out = buf;
  return total;
}

extern "C" int dbx_wire_decode(const uint8_t* data, size_t len, DbxOhlcv* out,
                               char* err, size_t errlen) {
  std::memset(out, 0, sizeof(*out));
  if (len < 8 || std::memcmp(data, "DBX1", 4) != 0) {
    set_err(err, errlen, "bad magic; not a DBX1 block");
    return 1;
  }
  uint32_t n;
  std::memcpy(&n, data + 4, 4);
  const size_t need = 8 + sizeof(float) * 5 * static_cast<size_t>(n);
  if (len < need) {
    set_err(err, errlen, "truncated DBX1 block");
    return 1;
  }
  float* bufs[5];
  size_t off = 8;
  for (int i = 0; i < 5; ++i) {
    bufs[i] = static_cast<float*>(std::malloc(sizeof(float) * n));
    std::memcpy(bufs[i], data + off, sizeof(float) * n);
    off += sizeof(float) * n;
  }
  out->n_bars = n;
  out->open = bufs[0];
  out->high = bufs[1];
  out->low = bufs[2];
  out->close = bufs[3];
  out->volume = bufs[4];
  return 0;
}

extern "C" void dbx_ohlcv_free(DbxOhlcv* o) {
  if (!o) return;
  std::free(o->open);
  std::free(o->high);
  std::free(o->low);
  std::free(o->close);
  std::free(o->volume);
  std::memset(o, 0, sizeof(*o));
}

extern "C" void dbx_bytes_free(uint8_t* p) { std::free(p); }

// ---------------------------------------------------------------------------
// Bounded MPMC blob queue
// ---------------------------------------------------------------------------

struct DbxQueue {
  explicit DbxQueue(size_t cap) : capacity(cap) {}
  const size_t capacity;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::deque<std::vector<uint8_t>> items;
  bool closed = false;
};

extern "C" DbxQueue* dbx_queue_new(size_t capacity) {
  return new DbxQueue(capacity ? capacity : 1);
}

static bool wait_on(std::condition_variable& cv,
                    std::unique_lock<std::mutex>& lk, int64_t timeout_ms,
                    const std::function<bool()>& pred) {
  if (timeout_ms < 0) {
    cv.wait(lk, pred);
    return true;
  }
  return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
}

extern "C" int dbx_queue_push(DbxQueue* q, const uint8_t* data, size_t len,
                              int64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(q->mu);
  const bool ok = wait_on(q->not_full, lk, timeout_ms, [q] {
    return q->closed || q->items.size() < q->capacity;
  });
  if (!ok) return 1;
  if (q->closed) return 2;
  q->items.emplace_back(data, data + len);
  q->not_empty.notify_one();
  return 0;
}

extern "C" int dbx_queue_push_front(DbxQueue* q, const uint8_t* data,
                                    size_t len, int64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(q->mu);
  const bool ok = wait_on(q->not_full, lk, timeout_ms, [q] {
    return q->closed || q->items.size() < q->capacity;
  });
  if (!ok) return 1;
  if (q->closed) return 2;
  q->items.emplace_front(data, data + len);
  q->not_empty.notify_one();
  return 0;
}

extern "C" int dbx_queue_pop(DbxQueue* q, uint8_t** data, size_t* len,
                             int64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(q->mu);
  const bool ok = wait_on(q->not_empty, lk, timeout_ms,
                          [q] { return q->closed || !q->items.empty(); });
  if (!ok) return 1;
  if (q->items.empty()) return 2;  // closed and drained
  std::vector<uint8_t> item = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  lk.unlock();
  *len = item.size();
  *data = static_cast<uint8_t*>(std::malloc(item.size() ? item.size() : 1));
  std::memcpy(*data, item.data(), item.size());
  return 0;
}

extern "C" void dbx_queue_close(DbxQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

extern "C" size_t dbx_queue_size(DbxQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

extern "C" void dbx_queue_free(DbxQueue* q) { delete q; }

// ---------------------------------------------------------------------------
// Job-queue state machine
// ---------------------------------------------------------------------------
//
// Mirrors rpc/dispatcher.py's Python fallback exactly; see dbx_core.h for
// the transition contract and the take_begin/take_commit race model.

struct DbxJobQueue {
  struct Lease {
    std::string worker;
    std::chrono::steady_clock::time_point deadline;
    uint64_t seq;  // insertion order, so requeue scans match the Python
                   // fallback's insertion-ordered dict iteration
  };
  // Int-handle design: every id registers once and gets a dense int32
  // index; all hot-path state is int-keyed (no string hashing per
  // transition) and the batch ABI moves int32 arrays, so a batch-32 RPC
  // costs one crossing carrying 128 bytes instead of 32 packed strings
  // (the string-keyed version measured SLOWER than the Python dict
  // fallback — per-id marshalling, not the transitions, was the cost).
  static constexpr uint8_t kCompleted = 1, kFailed = 2, kTombstone = 4;
  static constexpr double kUnregistered =
      std::numeric_limits<double>::quiet_NaN();

  std::mutex mu;
  std::vector<std::string> ids;                    // idx -> id
  std::unordered_map<std::string, int32_t> idx_of; // id -> idx
  std::vector<double> combos;       // idx-aligned; NaN = pending-only id
                                    // (pushed without register — the
                                    // Python fallback allows it; complete
                                    // reports it "unknown")
  std::vector<uint8_t> flags;       // idx-aligned kCompleted/kFailed/...
  std::vector<double> credited;     // idx-aligned combos credited
  std::deque<int32_t> pending;
  std::unordered_map<int32_t, Lease> leases;
  int64_t tombstoned = 0;           // invariant: every tombstone is in
                                    // the pending FIFO
  int64_t completed_count = 0;
  int64_t failed_count = 0;
  uint64_t lease_seq = 0;
  int64_t requeued = 0;
  double combos_done = 0.0;

  // idx for an id, creating the slot on first sight (combos NaN until
  // register fills it).
  int32_t intern(const char* id) {
    auto it = idx_of.find(id);
    if (it != idx_of.end()) return it->second;
    const int32_t idx = static_cast<int32_t>(ids.size());
    ids.emplace_back(id);
    idx_of.emplace(ids.back(), idx);
    combos.push_back(kUnregistered);
    flags.push_back(0);
    credited.push_back(0.0);
    return idx;
  }

  int32_t lookup(const char* id) const {
    auto it = idx_of.find(id);
    return it == idx_of.end() ? -1 : it->second;
  }
};

extern "C" DbxJobQueue* dbx_jobq_new(void) { return new DbxJobQueue(); }

extern "C" void dbx_jobq_free(DbxJobQueue* q) { delete q; }

extern "C" int dbx_jobq_register(DbxJobQueue* q, const char* id,
                                 double combos) {
  if (std::strlen(id) > DBX_JOBQ_MAX_ID) return 1;
  std::lock_guard<std::mutex> lk(q->mu);
  q->combos[q->intern(id)] = combos;
  return 0;
}

extern "C" void dbx_jobq_push_pending(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  q->pending.push_back(q->intern(id));
}

extern "C" void dbx_jobq_mark_completed(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  const int32_t idx = q->intern(id);
  if (!(q->flags[idx] & DbxJobQueue::kCompleted)) {
    // No combos_done credit: a restored completion's work happened in a
    // previous run.
    q->flags[idx] |= DbxJobQueue::kCompleted;
    ++q->completed_count;
  }
}

extern "C" void dbx_jobq_mark_failed(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  const int32_t idx = q->intern(id);
  if (!(q->flags[idx] & DbxJobQueue::kFailed)) {
    q->flags[idx] |= DbxJobQueue::kFailed;
    ++q->failed_count;
  }
}

namespace {

// Shared bodies of the single-id and batched transitions: both surfaces
// run these under one held lock, so they cannot drift.

inline int32_t take_begin_locked(DbxJobQueue* q) {
  while (!q->pending.empty()) {
    const int32_t idx = q->pending.front();
    q->pending.pop_front();
    if (q->flags[idx] & DbxJobQueue::kTombstone) {
      q->flags[idx] &= ~DbxJobQueue::kTombstone;  // completed while pending
      --q->tombstoned;
      continue;
    }
    return idx;
  }
  return -1;
}

inline int take_commit_locked(DbxJobQueue* q, int32_t idx, const char* worker,
                              std::chrono::steady_clock::time_point deadline) {
  if (q->flags[idx] & DbxJobQueue::kCompleted) {
    // Completed in the unlocked take window: drop the orphan tombstone the
    // completion installed, and do not lease.
    if (q->flags[idx] & DbxJobQueue::kTombstone) {
      q->flags[idx] &= ~DbxJobQueue::kTombstone;
      --q->tombstoned;
    }
    return 1;
  }
  q->leases[idx] = DbxJobQueue::Lease{worker, deadline, q->lease_seq++};
  return 0;
}

inline int complete_locked(DbxJobQueue* q, int32_t idx) {
  if (idx < 0 || static_cast<size_t>(idx) >= q->ids.size() ||
      std::isnan(q->combos[idx]))
    return 2;  // unknown: never registered with a combo credit
  const bool had_lease = q->leases.erase(idx) > 0;
  if (q->flags[idx] & DbxJobQueue::kCompleted) return 1;
  if (!had_lease && !(q->flags[idx] & DbxJobQueue::kFailed) &&
      !(q->flags[idx] & DbxJobQueue::kTombstone)) {
    // Completion for a job still sitting in the pending FIFO (late RPC
    // straddling a lease expiry or restart): no interior removal, so
    // tombstone the id for take to skip.
    q->flags[idx] |= DbxJobQueue::kTombstone;
    ++q->tombstoned;
  }
  q->flags[idx] |= DbxJobQueue::kCompleted;
  ++q->completed_count;
  q->credited[idx] = q->combos[idx];
  q->combos_done += q->combos[idx];
  return 0;
}

}  // namespace

extern "C" int dbx_jobq_take_begin(DbxJobQueue* q, char* out, size_t cap) {
  std::lock_guard<std::mutex> lk(q->mu);
  const int32_t idx = take_begin_locked(q);
  if (idx < 0) return 0;
  const std::string& id = q->ids[idx];
  if (id.size() + 1 > cap) {
    // Caller's buffer cannot hold the id (register caps ids at
    // DBX_JOBQ_MAX_ID, so a >=512-byte buffer never hits this). Put the
    // id back and report the contract violation — silently dropping a
    // popped job would drain the queue with work unprocessed.
    q->pending.push_front(idx);
    return -1;
  }
  std::memcpy(out, id.c_str(), id.size() + 1);
  return 1;
}

extern "C" int dbx_jobq_take_commit(DbxJobQueue* q, const char* id,
                                    const char* worker, int64_t lease_ms) {
  std::lock_guard<std::mutex> lk(q->mu);
  return take_commit_locked(
      q, q->intern(id), worker,
      std::chrono::steady_clock::now() + std::chrono::milliseconds(lease_ms));
}

extern "C" int dbx_jobq_fail(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  const int32_t idx = q->intern(id);
  if (q->flags[idx] & DbxJobQueue::kCompleted) {
    if (q->flags[idx] & DbxJobQueue::kTombstone) {
      q->flags[idx] &= ~DbxJobQueue::kTombstone;
      --q->tombstoned;
    }
    return 1;
  }
  if (!(q->flags[idx] & DbxJobQueue::kFailed)) {
    q->flags[idx] |= DbxJobQueue::kFailed;
    ++q->failed_count;
  }
  return 0;
}

extern "C" int dbx_jobq_complete(DbxJobQueue* q, const char* id) {
  std::lock_guard<std::mutex> lk(q->mu);
  return complete_locked(q, q->lookup(id));
}

extern "C" int dbx_jobq_enqueue_n(DbxJobQueue* q, const char* ids, int stride,
                                  const double* combos, int n) {
  std::lock_guard<std::mutex> lk(q->mu);
  int accepted = 0;
  const char* p = ids;
  for (int i = 0; i < n; ++i) {
    const char* id = stride > 0 ? ids + static_cast<size_t>(i) * stride : p;
    const size_t len = std::strlen(id);
    if (stride <= 0) p = id + len + 1;  // stride 0: NUL-separated pack
    if (len > DBX_JOBQ_MAX_ID) continue;
    const int32_t idx = q->intern(id);
    q->combos[idx] = combos[i];
    q->pending.push_back(idx);
    ++accepted;
  }
  return accepted;
}

extern "C" int dbx_jobq_take_begin_idx_n(DbxJobQueue* q, int32_t* out, int n) {
  std::lock_guard<std::mutex> lk(q->mu);
  int got = 0;
  while (got < n) {
    const int32_t idx = take_begin_locked(q);
    if (idx < 0) break;
    out[got++] = idx;
  }
  return got;
}

extern "C" int dbx_jobq_take_commit_idx_n(DbxJobQueue* q, const int32_t* idxs,
                                          int n, const char* worker,
                                          int64_t lease_ms,
                                          uint8_t* committed) {
  std::lock_guard<std::mutex> lk(q->mu);
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(lease_ms);
  int done = 0;
  for (int i = 0; i < n; ++i) {
    committed[i] = take_commit_locked(q, idxs[i], worker, deadline) == 0;
    done += committed[i];
  }
  return done;
}

extern "C" void dbx_jobq_complete_idx_n(DbxJobQueue* q, const int32_t* idxs,
                                        int n, uint8_t* outcomes) {
  std::lock_guard<std::mutex> lk(q->mu);
  for (int i = 0; i < n; ++i) {
    outcomes[i] = static_cast<uint8_t>(complete_locked(q, idxs[i]));
  }
}

namespace {

int requeue_matching(
    DbxJobQueue* q, DbxPrunedFn fn, void* ctx,
    const std::function<bool(const DbxJobQueue::Lease&)>& match) {
  std::vector<std::pair<uint64_t, int32_t>> hit;
  std::vector<std::string> hit_ids;  // copies made UNDER the lock: the
                                     // unlocked callback loop must not
                                     // read q->ids, which a concurrent
                                     // enqueue's intern() can reallocate
  {
    std::lock_guard<std::mutex> lk(q->mu);
    for (const auto& [idx, lease] : q->leases) {
      if (match(lease)) hit.emplace_back(lease.seq, idx);
    }
    // Lease-insertion order, so the front-of-queue result is identical to
    // the Python fallback's insertion-ordered scan + appendleft loop.
    std::sort(hit.begin(), hit.end());
    hit_ids.reserve(hit.size());
    for (const auto& [seq, idx] : hit) {
      (void)seq;
      q->leases.erase(idx);
      q->pending.push_front(idx);
      hit_ids.push_back(q->ids[idx]);
    }
    q->requeued += static_cast<int64_t>(hit.size());
  }
  if (fn) {
    for (const auto& id : hit_ids) {
      fn(id.c_str(), ctx);
    }
  }
  return static_cast<int>(hit.size());
}

}  // namespace

extern "C" int dbx_jobq_requeue_expired(DbxJobQueue* q, DbxPrunedFn fn,
                                        void* ctx) {
  const auto now = std::chrono::steady_clock::now();
  return requeue_matching(
      q, fn, ctx,
      [now](const DbxJobQueue::Lease& l) { return l.deadline <= now; });
}

extern "C" int dbx_jobq_requeue_worker(DbxJobQueue* q, const char* worker,
                                       DbxPrunedFn fn, void* ctx) {
  const std::string w = worker;
  return requeue_matching(
      q, fn, ctx, [&w](const DbxJobQueue::Lease& l) { return l.worker == w; });
}

extern "C" void dbx_jobq_stats(DbxJobQueue* q, DbxJobqStats* out) {
  std::lock_guard<std::mutex> lk(q->mu);
  out->pending = static_cast<int64_t>(q->pending.size()) - q->tombstoned;
  out->leased = static_cast<int64_t>(q->leases.size());
  out->completed = q->completed_count;
  out->requeued = q->requeued;
  out->failed = q->failed_count;
  out->combos_done = q->combos_done;
}

extern "C" int dbx_jobq_drained(DbxJobQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  const int64_t live =
      static_cast<int64_t>(q->pending.size()) - q->tombstoned;
  return (live == 0 && q->leases.empty()) ? 1 : 0;
}
// ---------------------------------------------------------------------------
// Peer registry
// ---------------------------------------------------------------------------

struct DbxRegistry {
  explicit DbxRegistry(int64_t window) : window_ms(window) {}
  const int64_t window_ms;
  std::mutex mu;
  std::unordered_map<std::string, std::chrono::steady_clock::time_point> peers;
};

extern "C" DbxRegistry* dbx_registry_new(int64_t prune_window_ms) {
  return new DbxRegistry(prune_window_ms);
}

extern "C" int dbx_registry_touch(DbxRegistry* r, const char* peer_id) {
  std::lock_guard<std::mutex> lk(r->mu);
  auto now = std::chrono::steady_clock::now();
  auto [it, inserted] = r->peers.insert_or_assign(peer_id, now);
  (void)it;
  return inserted ? 1 : 0;
}

extern "C" int dbx_registry_prune(DbxRegistry* r, DbxPrunedFn fn, void* ctx) {
  std::vector<std::string> dead;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    const auto cutoff = std::chrono::steady_clock::now() -
                        std::chrono::milliseconds(r->window_ms);
    for (auto it = r->peers.begin(); it != r->peers.end();) {
      if (it->second < cutoff) {
        dead.push_back(it->first);
        it = r->peers.erase(it);
      } else {
        ++it;
      }
    }
  }
  if (fn) {
    for (const auto& id : dead) fn(id.c_str(), ctx);
  }
  return static_cast<int>(dead.size());
}

extern "C" int dbx_registry_alive(DbxRegistry* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  return static_cast<int>(r->peers.size());
}

extern "C" void dbx_registry_free(DbxRegistry* r) { delete r; }
